"""ImageTransformer: a stage pipeline of image ops (reference
``opencv/.../ImageTransformer.scala:31-429``), OpenCV-free.

Each stage is a small dataclass with an ``apply(img) -> img`` on HWC float32
numpy arrays; ``ImageTransformer`` chains them per image, then optionally
normalizes (means/stds/scale, ``ImageTransformer.scala:379-399``) and emits
either HWC images or a stacked [N, C, H, W] tensor column for DNN input
(``ImageTransformer.scala:413``).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Transformer

__all__ = ["ImageTransformer", "Resize", "Crop", "CenterCrop", "ColorFormat",
           "Flip", "GaussianBlur", "Threshold", "as_image"]


def as_image(x) -> np.ndarray:
    """Coerce to HWC float32 (grayscale promoted to 1 channel)."""
    img = np.asarray(x, dtype=np.float32)
    if img.ndim == 2:
        img = img[:, :, None]
    if img.ndim != 3:
        raise ValueError(f"expected HW or HWC image, got shape {img.shape}")
    return img


def bilinear_resize(img: np.ndarray, height: int, width: int) -> np.ndarray:
    """Vectorized bilinear resample (align_corners=False convention, matching
    OpenCV INTER_LINEAR / jax.image.resize('linear'))."""
    H, W, C = img.shape
    if (H, W) == (height, width):
        return img
    ys = (np.arange(height) + 0.5) * H / height - 0.5
    xs = (np.arange(width) + 0.5) * W / width - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, H - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, W - 1)
    y1 = np.clip(y0 + 1, 0, H - 1)
    x1 = np.clip(x0 + 1, 0, W - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :, None]
    top = img[y0][:, x0] * (1 - wx) + img[y0][:, x1] * wx
    bot = img[y1][:, x0] * (1 - wx) + img[y1][:, x1] * wx
    return (top * (1 - wy) + bot * wy).astype(np.float32)


def gaussian_kernel1d(sigma: float, radius: int | None = None) -> np.ndarray:
    if radius is None:
        radius = max(int(round(3.0 * sigma)), 1)
    x = np.arange(-radius, radius + 1, dtype=np.float64)
    k = np.exp(-0.5 * (x / max(sigma, 1e-8)) ** 2)
    return (k / k.sum()).astype(np.float32)


def _sep_conv(img: np.ndarray, kx: np.ndarray, ky: np.ndarray) -> np.ndarray:
    """Separable 2D convolution with edge replication (OpenCV BORDER_REPLICATE)."""
    ry, rx = len(ky) // 2, len(kx) // 2
    pad = np.pad(img, ((ry, ry), (rx, rx), (0, 0)), mode="edge")
    # convolve rows then columns via strided sums
    out = np.zeros((img.shape[0] + 2 * ry, img.shape[1], img.shape[2]), np.float32)
    for i, w in enumerate(kx):
        out += w * pad[:, i : i + img.shape[1], :]
    final = np.zeros_like(img)
    for j, w in enumerate(ky):
        final += w * out[j : j + img.shape[0], :, :]
    return final


@dataclasses.dataclass
class Resize:
    """(ref ``ImageTransformer.scala`` ResizeImage) — keep_aspect_ratio resizes
    the short side to ``size`` (then callers usually CenterCrop)."""

    height: int = -1
    width: int = -1
    size: int = -1  # short-side mode when >0
    keep_aspect_ratio: bool = False

    def apply(self, img: np.ndarray) -> np.ndarray:
        H, W, _ = img.shape
        if self.size > 0 or self.keep_aspect_ratio:
            s = self.size if self.size > 0 else max(self.height, self.width)
            scale = s / min(H, W)
            return bilinear_resize(img, max(int(round(H * scale)), 1),
                                   max(int(round(W * scale)), 1))
        return bilinear_resize(img, self.height, self.width)


@dataclasses.dataclass
class Crop:
    x: int = 0
    y: int = 0
    height: int = 0
    width: int = 0

    def apply(self, img: np.ndarray) -> np.ndarray:
        return img[self.y : self.y + self.height, self.x : self.x + self.width]


@dataclasses.dataclass
class CenterCrop:
    height: int = 0
    width: int = 0

    def apply(self, img: np.ndarray) -> np.ndarray:
        H, W, _ = img.shape
        y = max((H - self.height) // 2, 0)
        x = max((W - self.width) // 2, 0)
        return img[y : y + self.height, x : x + self.width]


@dataclasses.dataclass
class ColorFormat:
    """'rgb' <-> 'bgr' swap or 'gray' (ITU-R BT.601 luma, what OpenCV uses)."""

    format: str = "rgb"

    def apply(self, img: np.ndarray) -> np.ndarray:
        f = self.format.lower()
        if f in ("bgr", "rgb"):  # symmetric channel swap
            return img[:, :, ::-1] if img.shape[2] == 3 else img
        if f in ("gray", "grayscale"):
            if img.shape[2] == 1:
                return img
            w = np.array([0.299, 0.587, 0.114], np.float32)
            return (img[:, :, :3] @ w)[:, :, None]
        raise ValueError(f"unknown color format {self.format!r}")


@dataclasses.dataclass
class Flip:
    """flip_code: 0 = vertical (around x-axis), 1 = horizontal, -1 = both
    (OpenCV convention, ``ImageTransformer.scala`` Flip stage)."""

    flip_code: int = 1

    def apply(self, img: np.ndarray) -> np.ndarray:
        if self.flip_code == 0:
            return img[::-1]
        if self.flip_code > 0:
            return img[:, ::-1]
        return img[::-1, ::-1]


@dataclasses.dataclass
class GaussianBlur:
    """Covers both Blur (box ~ sigma from aperture) and GaussianKernel stages."""

    aperture_size: int = 0
    sigma: float = 1.0

    def apply(self, img: np.ndarray) -> np.ndarray:
        radius = self.aperture_size // 2 if self.aperture_size > 0 else None
        k = gaussian_kernel1d(self.sigma, radius)
        return _sep_conv(img, k, k)


@dataclasses.dataclass
class Threshold:
    """Binary threshold (ref Threshold stage): pixel > threshold ? max_val : 0."""

    threshold: float = 127.0
    max_val: float = 255.0

    def apply(self, img: np.ndarray) -> np.ndarray:
        return np.where(img > self.threshold, np.float32(self.max_val), np.float32(0.0))


class ImageTransformer(Transformer):
    """Chain of image stages + normalization + optional tensor output
    (ref ``opencv/.../ImageTransformer.scala:429``).

    ``set_to_tensor(True)`` emits a [C, H, W] float32 array per row (stacked
    into a rectangular column when sizes agree) — the DNN input format
    (`ImageTransformer.scala:413`); otherwise HWC images come back.
    """

    feature_name = "image"

    input_col = Param("input_col", "image column", default="image")
    output_col = Param("output_col", "output column", default="out_image")
    stages = ComplexParam("stages", "ordered list of image stage objects", default=None)
    color_scale_factor = Param("color_scale_factor", "multiply pixels (e.g. 1/255)",
                               default=None)
    norm_means = ComplexParam("norm_means", "per-channel means subtracted after scaling",
                              default=None)
    norm_stds = ComplexParam("norm_stds", "per-channel stds divided after scaling",
                             default=None)
    to_tensor = Param("to_tensor", "emit CHW float tensor", default=False,
                      converter=TypeConverters.to_bool)

    # -------- fluent stage builders (mirroring the reference's API) --------
    def _add(self, stage) -> "ImageTransformer":
        cur = list(self.get("stages") or [])
        cur.append(stage)
        return self.set(stages=cur)

    def resize(self, height: int = -1, width: int = -1, size: int = -1,
               keep_aspect_ratio: bool = False) -> "ImageTransformer":
        return self._add(Resize(height, width, size, keep_aspect_ratio))

    def crop(self, x: int, y: int, height: int, width: int) -> "ImageTransformer":
        return self._add(Crop(x, y, height, width))

    def center_crop(self, height: int, width: int) -> "ImageTransformer":
        return self._add(CenterCrop(height, width))

    def color_format(self, format: str) -> "ImageTransformer":
        return self._add(ColorFormat(format))

    def flip(self, flip_code: int = 1) -> "ImageTransformer":
        return self._add(Flip(flip_code))

    def gaussian_blur(self, aperture_size: int = 0, sigma: float = 1.0) -> "ImageTransformer":
        return self._add(GaussianBlur(aperture_size, sigma))

    def threshold(self, threshold: float = 127.0, max_val: float = 255.0) -> "ImageTransformer":
        return self._add(Threshold(threshold, max_val))

    def normalize(self, means, stds, color_scale_factor: float = 1.0 / 255.0) -> "ImageTransformer":
        self.set(norm_means=list(means), norm_stds=list(stds),
                 color_scale_factor=color_scale_factor)
        return self.set(to_tensor=True)

    # -------- transform --------
    def _process_one(self, x) -> np.ndarray:
        img = as_image(x)
        for stage in self.get("stages") or []:
            img = stage.apply(img)
        scale = self.get("color_scale_factor")
        means, stds = self.get("norm_means"), self.get("norm_stds")
        if scale is not None or means is not None or stds is not None:
            img = img * np.float32(scale if scale is not None else 1.0)
            if means is not None:
                img = img - np.asarray(means, np.float32)
            if stds is not None:
                img = img / np.asarray(stds, np.float32)
        if self.get("to_tensor"):
            img = np.transpose(img, (2, 0, 1))  # CHW
        return img.astype(np.float32)

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))

        def per_part(p):
            imgs = [self._process_one(x) for x in p[self.get("input_col")]]
            shapes = {im.shape for im in imgs}
            if len(shapes) == 1 and imgs:  # rectangular -> stacked tensor column
                return np.stack(imgs)
            out = np.empty(len(imgs), dtype=object)
            out[:] = imgs
            return out

        return df.with_column(self.get("output_col"), per_part)
