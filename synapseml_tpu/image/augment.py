"""ImageSetAugmenter (reference ``opencv/.../ImageSetAugmenter.scala:18``):
train-time dataset expansion by horizontal/vertical flips — emits the original
rows plus one extra copy per enabled flip."""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param, TypeConverters
from ..core.pipeline import Transformer
from .transforms import Flip, as_image

__all__ = ["ImageSetAugmenter"]


class ImageSetAugmenter(Transformer):
    feature_name = "image"

    input_col = Param("input_col", "image column", default="image")
    output_col = Param("output_col", "augmented image column", default="image")
    flip_left_right = Param("flip_left_right", "add horizontal flips", default=True,
                            converter=TypeConverters.to_bool)
    flip_up_down = Param("flip_up_down", "add vertical flips", default=False,
                         converter=TypeConverters.to_bool)

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        ic, oc = self.get("input_col"), self.get("output_col")

        def flipped(code: int):
            f = Flip(code)

            def per_part(p):
                q = dict(p)
                imgs = [f.apply(as_image(x)) for x in p[ic]]
                if len({im.shape for im in imgs}) == 1 and imgs:
                    q[oc] = np.stack(imgs)
                else:
                    col = np.empty(len(imgs), dtype=object)
                    col[:] = imgs
                    q[oc] = col
                return q

            return df.map_partitions(per_part)

        base = df if oc == ic else df.with_column(
            oc, lambda p: p[ic])
        out = base
        if self.get("flip_left_right"):
            out = out.union(flipped(1))
        if self.get("flip_up_down"):
            out = out.union(flipped(0))
        return out
