"""Image preprocessing — the OpenCV module rebuilt without OpenCV.

Reference (SURVEY.md §2.4): ``opencv/.../ImageTransformer.scala`` (stage
pipeline over OpenCV JNI Mats), ``ImageSetAugmenter.scala:18``,
``core/.../image/UnrollImage.scala``, ``core/.../image/Superpixel.scala``.

TPU-native design: images are numpy HWC arrays in DataFrame columns (ragged
sizes allowed via object columns). Per-image geometry ops (resize/crop/flip)
are vectorized numpy on the host data plane; the *output* of the pipeline is a
rectangular [N, C, H, W] float tensor column sized for the device — the whole
point of the preprocessing stage is to produce static-shaped, batched input
for jitted model transformers (cf. ImageTransformer's toTensor mode,
``ImageTransformer.scala:413``).
"""

from .transforms import (
    CenterCrop,
    ColorFormat,
    Crop,
    Flip,
    GaussianBlur,
    ImageTransformer,
    Resize,
    Threshold,
)
from .augment import ImageSetAugmenter
from .unroll import UnrollBinaryImage, UnrollImage
from .superpixel import SuperpixelTransformer, slic_segments

__all__ = [
    "ImageTransformer", "Resize", "Crop", "CenterCrop", "ColorFormat", "Flip",
    "GaussianBlur", "Threshold", "ImageSetAugmenter", "UnrollImage", "UnrollBinaryImage",
    "SuperpixelTransformer", "slic_segments",
]
