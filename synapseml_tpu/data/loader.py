"""Streaming DataLoader: seeded shuffles, bucketed batches, async prefetch.

The input-pipeline discipline the MPI/TensorFlow characterization work
(PAPERS.md, arXiv:1810.11112) shows caps scaling: accelerator steps must
overlap with input I/O, not alternate with it. The loader runs a background
producer thread that reads shards (under a ``data.prefetch`` tracer span,
with the source's retry/fault guards), assembles fixed-shape batches through
the :mod:`core.batching` bucket ladder, optionally ``jax.device_put``-places
the NEXT batch while the current step runs (double buffering via
``place_fn``), and hands them over a bounded queue — backpressure, never an
unbounded pileup.

Determinism + resume: the batch stream is a pure function of
``(seed, epoch, shard layout)`` (see :mod:`~synapseml_tpu.data.state`), and
every emitted batch records an :class:`IteratorState` snapshot, so a
checkpoint taken after batch *k* restores a loader that continues with batch
*k+1* bit-identically — no replayed, no skipped rows.

Observability: queue-depth gauge, consumer wait-time + shard-read
histograms, rows/rows-per-sec series, all in the unified metrics registry
(``synapseml_data_*``), plus one span per prefetched shard.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterator

import numpy as np

from ..core import batching as cb
from ..core import observability as obs
from .source import ShardedSource, _n_rows
from .state import ElasticPlan, IteratorState, row_order, shard_order

__all__ = ["DataLoader", "ElasticStreamSet"]

_END = object()

_LOADER_METRICS = obs.HandleCache(lambda reg: {
    "queue_depth": reg.gauge(
        "synapseml_data_prefetch_queue_depth",
        "batches currently buffered ahead of the training loop", ("source",)),
    "wait_ms": reg.histogram(
        "synapseml_data_batch_wait_ms",
        "time the training loop blocked waiting for the next batch",
        ("source",)),
    "read_ms": reg.histogram(
        "synapseml_data_shard_read_ms",
        "wall time of one shard read + row-order assembly", ("source",)),
    "rows": reg.counter(
        "synapseml_data_rows_total",
        "rows emitted into training batches", ("source",)),
    "rows_per_sec": reg.gauge(
        "synapseml_data_rows_per_sec",
        "loader throughput since iteration started", ("source",)),
})


class DataLoader:
    """One-shot iterator of training batches over a :class:`ShardedSource`.

    Each batch is a dict of numpy (or device, with ``place_fn``) arrays plus
    a ``_valid`` float32 mask covering bucket padding. Full batches pad to
    ``round_up(batch_size, multiple_of)``; a short epoch tail (only with
    ``drop_remainder=False``) pads to its own :class:`core.batching`
    ladder rung, so a variable tail never compiles more than ladder-many
    step shapes.

    ``host_index``/``host_count`` default to the JAX process topology —
    hosts take disjoint strided slices of the epoch's seeded shard order.

    ``state``: resume cursor from a checkpoint (see
    :meth:`state_for_batch` / ``models.trainer.fit_source``).
    """

    def __init__(self, source: ShardedSource, batch_size: int, *,
                 seed: int = 0, epochs: int | None = None,
                 drop_remainder: bool = True, shuffle_shards: bool = True,
                 shuffle_rows: str = "full", shuffle_window: int = 4096,
                 multiple_of: int = 1, bucketer: cb.ShapeBucketer | None = None,
                 prefetch: int = 2, place_fn: Callable[[dict], dict] | None = None,
                 host_index: int | None = None, host_count: int | None = None,
                 columns: list[str] | None = None,
                 state: IteratorState | None = None,
                 state_history: int = 64):
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        self.source = source
        self.batch_size = int(batch_size)
        self.epochs = epochs
        self.drop_remainder = bool(drop_remainder)
        self.shuffle_shards = bool(shuffle_shards)
        self.shuffle_rows = shuffle_rows
        self.shuffle_window = int(shuffle_window)
        self.multiple_of = max(int(multiple_of), 1)
        self.bucketer = bucketer or cb.default_bucketer()
        self.place_fn = place_fn
        self.columns = list(columns) if columns else None
        from .source import resolve_host

        self.host_index, self.host_count = resolve_host(host_index,
                                                        host_count)

        st = state.copy() if state is not None else IteratorState(seed=int(seed))
        if state is not None and st.seed != int(seed):
            raise ValueError(f"resume state was recorded under seed {st.seed}, "
                             f"loader constructed with seed {seed}")
        if st.shard_counts is None:
            st.shard_counts = np.full(source.num_shards, -1, np.int64)
        elif st.shard_counts.shape[0] != source.num_shards:
            raise ValueError(
                f"resume state knows {st.shard_counts.shape[0]} shards but "
                f"the source has {source.num_shards} — shard layout changed "
                "since the checkpoint was written")
        self._state = st

        self._q: "queue.Queue" = queue.Queue(maxsize=max(int(prefetch), 1))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # bounded per-batch state ring: checkpointers query the state of a
        # batch at most (trainer prefetch + scan chunk) behind the newest
        # consumed one, so a short history suffices — an unbounded dict
        # would leak one shard_counts copy per batch on checkpointer-less
        # runs
        self._snapshots: dict[int, IteratorState] = {}
        self._state_history = max(int(state_history), 1)
        self._snap_lock = threading.Lock()
        self._schema_keys: tuple | None = tuple(columns) if columns else None
        self._exhausted = False
        # local stat mirrors (cheap to read in bench loops / tests)
        self._wait_s = 0.0
        self._t_start: float | None = None
        self._rows_out = 0
        self._batches_out = 0
        self._occupancy_sum = 0
        self._full_bucket = cb.round_up_to_multiple(self.batch_size,
                                                    self.multiple_of)

    # -- iteration ----------------------------------------------------------
    def __iter__(self) -> Iterator[dict]:
        if self._thread is None:
            self._t_start = time.perf_counter()
            self._thread = threading.Thread(target=self._producer, daemon=True)
            self._thread.start()
        return self

    def __next__(self) -> dict:
        if self._thread is None:
            iter(self)
        if self._exhausted:
            raise StopIteration
        m = _LOADER_METRICS.get()
        t0 = time.perf_counter()
        while True:
            # timed get + stop check: close() can race its _END sentinel
            # against an in-flight producer put (prefetch=1), so a blocked
            # consumer must also notice the stop flag itself
            try:
                item = self._q.get(timeout=0.5)
                break
            except queue.Empty:
                if self._stop.is_set():
                    self._exhausted = True
                    raise StopIteration from None
        wait = time.perf_counter() - t0
        self._wait_s += wait
        m["wait_ms"].observe(wait * 1e3, source=self.source.name)
        self._occupancy_sum += self._q.qsize()
        m["queue_depth"].set(self._q.qsize(), source=self.source.name)
        if item is _END:
            self._exhausted = True
            raise StopIteration
        if isinstance(item, BaseException):
            self._exhausted = True
            raise item
        batch, snap, n_valid = item
        with self._snap_lock:
            self._snapshots[snap.batches_emitted] = snap
            while len(self._snapshots) > self._state_history:
                self._snapshots.pop(next(iter(self._snapshots)))
        self._batches_out += 1
        self._rows_out += n_valid
        m["rows"].inc(n_valid, source=self.source.name)
        dt = max(time.perf_counter() - self._t_start, 1e-9)
        m["rows_per_sec"].set(self._rows_out / dt, source=self.source.name)
        return batch

    def close(self) -> None:
        """Stop the producer (idempotent; the thread drains on its own)."""
        self._stop.set()
        while True:  # unblock a producer stuck on a full queue
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        try:  # wake a consumer blocked in __next__'s untimed get()
            self._q.put_nowait(_END)
        except queue.Full:
            pass

    def __del__(self):  # abandoned mid-stream (e.g. fit hit max_steps)
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    # -- checkpoint surface -------------------------------------------------
    def state_for_batch(self, batches_emitted: int) -> IteratorState | None:
        """The iterator state as of (just after) global batch
        ``batches_emitted`` — what a checkpoint taken at optimizer step N
        (one batch per step) should carry. Older snapshots are pruned."""
        with self._snap_lock:
            snap = self._snapshots.get(int(batches_emitted))
            for k in [k for k in self._snapshots if k < int(batches_emitted)]:
                del self._snapshots[k]
        return snap

    def stats(self) -> dict:
        """Local mirrors of the loader series (bench/test surface)."""
        wall = (time.perf_counter() - self._t_start) if self._t_start else 0.0
        return {
            "batches": self._batches_out,
            "rows": self._rows_out,
            "rows_per_sec": self._rows_out / wall if wall > 0 else 0.0,
            "wait_s_total": self._wait_s,
            "stall_fraction": self._wait_s / wall if wall > 0 else 0.0,
            "mean_queue_occupancy": (self._occupancy_sum / self._batches_out
                                     if self._batches_out else 0.0),
            "queue_depth": self._q.qsize(),
        }

    # -- producer -----------------------------------------------------------
    def _conform(self, cols: dict, shard) -> dict:
        """Pin every shard to ONE schema: the ``columns`` selection, or the
        first shard's key set. Later shards' extra keys are dropped (they
        could not batch against earlier shards' arrays anyway); a MISSING
        key fails fast with the shard named — far better than a KeyError
        deep inside batch concatenation, and heterogeneous jsonl corpora
        get pointed at ``columns=[...]``."""
        if self._schema_keys is None:
            self._schema_keys = tuple(cols)
        missing = [k for k in self._schema_keys if k not in cols]
        if missing:
            raise ValueError(
                f"shard {shard.target} is missing column(s) {missing} "
                f"(stream schema {list(self._schema_keys)}); streamed "
                "batches need a uniform schema — pass columns=[...] to "
                "select the shared columns")
        return {k: cols[k] for k in self._schema_keys}

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _emit(self, buffers: list[dict], count: int, bucket: int,
              state: IteratorState) -> tuple[dict, IteratorState] | None:
        """Assemble the first ``count`` buffered rows into one padded batch +
        the post-batch state snapshot. Only the leading buffers covering
        ``count`` rows are touched — a large shard remainder is never
        re-concatenated per batch."""
        take, need = [], count
        for b in buffers:
            n = _n_rows(b)
            t = min(n, need)
            take.append({k: np.asarray(v)[:t] for k, v in b.items()}
                        if t < n else b)
            need -= t
            if need == 0:
                break
        cols = {k: (np.concatenate([np.asarray(b[k]) for b in take])
                    if len(take) > 1 else take[0][k])
                for k in take[0]}
        batch = {}
        for k, v in cols.items():
            v = np.asarray(v)
            if v.dtype == object:
                raise TypeError(
                    f"column {k!r} is object-dtype; featurize it into a "
                    "rectangular array before streaming (or pass columns=[...] "
                    "to select trainable columns)")
            batch[k] = cb.pad_rows(v[:count], bucket)
        mask = np.zeros(bucket, np.float32)
        mask[:count] = 1.0
        batch["_valid"] = mask
        if self.place_fn is not None:
            batch = self.place_fn(batch)
        # n_valid rides host-side: the consumer must never fetch the (maybe
        # device-placed) mask back just to count rows
        return batch, state.copy(), count

    def _producer(self) -> None:
        try:
            self._produce()
        except BaseException as e:  # surface reader errors to the consumer
            self._put(e)

    def _produce(self) -> None:
        st = self._state
        m = _LOADER_METRICS.get()
        tracer = obs.get_tracer()
        bs = self.batch_size
        shards_list = self.source.shards()
        while self.epochs is None or st.epoch < self.epochs:
            order = shard_order(st.seed, st.epoch, self.source.num_shards,
                                self.shuffle_shards)
            mine = order[self.host_index::self.host_count]
            # resume fast-forward: skip whole shards already emitted this
            # epoch (their counts are known from the checkpoint), then skip
            # the consumed prefix of the boundary shard
            to_skip = st.rows_emitted
            start_pos = 0
            while start_pos < len(mine) and to_skip > 0:
                c = int(st.shard_counts[mine[start_pos]])
                if c < 0 or to_skip < c:
                    break
                to_skip -= c
                start_pos += 1
            buffers: list[dict] = []
            buffered = 0
            emitted_this_epoch = st.rows_emitted
            fresh_epoch = st.rows_emitted == 0
            puts_this_epoch = 0
            for pos in range(start_pos, len(mine)):
                if self._stop.is_set():
                    return
                si = int(mine[pos])
                shard = shards_list[si]
                t0 = time.perf_counter()
                with tracer.span("data.prefetch",
                                 {"shard": si, "target": shard.target,
                                  "epoch": st.epoch}):
                    cols = self.source.read_shard(shard)
                    if not cols:  # degenerate shard (zero rows, no schema)
                        st.shard_counts[si] = 0
                        continue
                    cols = self._conform(cols, shard)
                    n = _n_rows(cols)
                    st.shard_counts[si] = n
                    idx = row_order(st.seed, st.epoch, si, n,
                                    self.shuffle_rows, self.shuffle_window)
                    if to_skip > 0:
                        idx = idx[to_skip:]
                        to_skip = 0
                    cols = {k: np.asarray(v)[idx] for k, v in cols.items()}
                m["read_ms"].observe((time.perf_counter() - t0) * 1e3,
                                     source=self.source.name)
                if len(idx) == 0:
                    continue
                buffers.append(cols)
                buffered += len(idx)
                while buffered >= bs:
                    emitted_this_epoch += bs
                    snap = IteratorState(
                        epoch=st.epoch, rows_emitted=emitted_this_epoch,
                        batches_emitted=st.batches_emitted + 1, seed=st.seed,
                        shard_counts=st.shard_counts)
                    out = self._emit(buffers, bs, self._full_bucket, snap)
                    buffers, buffered = _carry(buffers, bs, buffered)
                    st.batches_emitted += 1
                    puts_this_epoch += 1
                    if not self._put(out):
                        return
            # epoch tail
            if buffered and not self.drop_remainder:
                bucket = min(self.bucketer.bucket_for(buffered,
                                                      self.multiple_of),
                             self._full_bucket)
                snap = IteratorState(
                    epoch=st.epoch + 1, rows_emitted=0,
                    batches_emitted=st.batches_emitted + 1, seed=st.seed,
                    shard_counts=st.shard_counts)
                out = self._emit(buffers, buffered, bucket, snap)
                st.batches_emitted += 1
                puts_this_epoch += 1
                if not self._put(out):
                    return
            if fresh_epoch and puts_this_epoch == 0:
                # A FULL epoch produced nothing — with epochs=None the loop
                # would otherwise spin re-reading the dataset forever while
                # the consumer blocks.
                if buffered == 0:
                    raise ValueError(
                        f"epoch {st.epoch} emitted no batches: this host's "
                        f"shard slice ({len(mine)} of "
                        f"{self.source.num_shards} shard(s)) produced no "
                        "rows — empty source, or more hosts than shards")
                raise ValueError(
                    f"epoch {st.epoch} emitted no batches: this host's "
                    f"shard slice holds {buffered} row(s) < "
                    f"batch_size={bs} and drop_remainder=True drops the "
                    "tail — lower batch_size or pass drop_remainder=False")
            st.epoch += 1
            st.rows_emitted = 0
        self._put(_END)


class ElasticStreamSet:
    """One gang member's view of an elastic run's batch streams.

    An :class:`~synapseml_tpu.data.state.ElasticPlan` freezes the run as
    ``orig_world`` virtual streams; this set owns the streams assigned to
    ``rank`` of ``world`` survivors — one :class:`DataLoader` per stream,
    each pinned to ``host_index=stream_id, host_count=orig_world`` and
    resumed from that stream's checkpointed cursor. Each step draws from
    the LEAST-consumed assigned stream (ties to the lowest stream id):
    with equal cursors this is plain round-robin, and because the choice
    is a function of the checkpointed cursors — never of a host-local
    cycle position — a resume landing mid-cycle continues the exact
    interleaving an uninterrupted run would have produced. The batch
    sequence is a pure function of ``(plan, rank, world)``.

    ``state_for_batch(k)`` returns the per-stream cursor dict after this
    host's k-th emitted batch — the ``data_iter`` payload a coordinated
    checkpoint stores per host (keys are stream ids, so
    ``ElasticPlan.from_host_states`` can reunite all N across ranks).
    """

    def __init__(self, source, batch_size: int, plan: ElasticPlan,
                 rank: int, world: int, *, prefetch: int = 2,
                 state_history: int = 64, **loader_kwargs):
        if not 0 <= int(rank) < int(world):
            raise ValueError(f"rank {rank} outside world {world}")
        self.plan = plan
        self.rank, self.world = int(rank), int(world)
        self.streams = plan.assignment(world)[self.rank]
        if not self.streams:
            raise ValueError(
                f"rank {rank} of {world} has no virtual streams — the run "
                f"was launched with orig_world={plan.orig_world} and only "
                "that many hosts can be fed; clamp world to <= orig_world "
                "in the launcher (fit_gang_source rejects this earlier "
                "with the same guidance)")
        loader_kwargs.pop("host_index", None)
        loader_kwargs.pop("host_count", None)
        self.loaders = []
        self._counts = []
        for sid in self.streams:
            st = IteratorState.from_tree(plan.states[sid])
            self.loaders.append(DataLoader(
                source, batch_size, seed=st.seed, state=st,
                host_index=sid, host_count=plan.orig_world,
                prefetch=prefetch, state_history=state_history,
                **loader_kwargs))
            self._counts.append(st.batches_emitted)
        self.emitted = 0
        self._exhausted: set[int] = set()
        self._snaps: dict[int, dict] = {}
        self._last_snap: dict | None = None
        self._history = max(int(state_history), 1)

    def __iter__(self):
        return self

    def __next__(self) -> dict:
        # A finite-epoch run's streams need not exhaust together (odd
        # shard counts): a dry stream leaves the rotation and the set
        # ends only when EVERY assigned stream is dry — ending on the
        # first StopIteration would silently drop the longer streams'
        # tail batches, breaking the zero-skipped-rows guarantee.
        # Exhaustion is a function of plan + source content, so the
        # interleaving stays the one an uninterrupted run produces.
        while True:
            live = [j for j in range(len(self.loaders))
                    if j not in self._exhausted]
            if not live:
                raise StopIteration
            i = min(live, key=lambda j: (self._counts[j], self.streams[j]))
            try:
                batch = next(self.loaders[i])
                break
            except StopIteration:
                self._exhausted.add(i)
        self._counts[i] += 1
        self.emitted += 1

        def cursor(j, sid):
            st = self.loaders[j].state_for_batch(self._counts[j])
            if st is None:  # stream not stepped yet this run: plan cursor
                return dict(self.plan.states[sid])
            return st.to_tree()

        if self._last_snap is None:  # first emit: all streams, once
            snap = {str(sid): cursor(j, sid)
                    for j, sid in enumerate(self.streams)}
        else:
            # only stream i advanced since the previous snapshot — a lone
            # survivor serving all N virtual streams must pay ONE cursor
            # serialization per optimizer step, not N
            snap = dict(self._last_snap)
            snap[str(self.streams[i])] = cursor(i, self.streams[i])
        self._last_snap = snap
        self._snaps[self.emitted] = snap
        while len(self._snaps) > self._history:
            self._snaps.pop(next(iter(self._snaps)))
        return batch

    def state_for_batch(self, emitted: int) -> dict | None:
        """Per-stream cursors after this host's ``emitted``-th post-resume
        batch (``{stream_id: IteratorState tree}``)."""
        return self._snaps.get(int(emitted))

    def close(self) -> None:
        for ld in self.loaders:
            ld.close()


def _carry(buffers: list[dict], consumed: int, buffered: int
           ) -> tuple[list[dict], int]:
    """Drop ``consumed`` rows off the front of the buffer chain."""
    left = consumed
    out = []
    for b in buffers:
        n = _n_rows(b)
        if left >= n:
            left -= n
            continue
        out.append({k: np.asarray(v)[left:] for k, v in b.items()}
                   if left else b)
        left = 0
    return out, buffered - consumed
