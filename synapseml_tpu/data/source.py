"""Out-of-core sharded data sources.

The Spark role being replaced (SURVEY.md §3.2): executors stream file splits
to the compute engines, so no host ever materializes the full dataset. Here a
:class:`ShardedSource` describes a dataset as a list of :class:`Shard`
descriptors — byte ranges of jsonl/csv files, row ranges of ``.npy`` arrays,
slices of an image directory listing — and ``read_shard`` materializes ONE
shard as a columnar dict. Memory is bounded by the shard size, not the
dataset size; the :mod:`~synapseml_tpu.data.loader` streams shards through a
background prefetcher into the training loop.

Per-host assignment follows the ``parallel/mesh`` process topology: every
host computes the same seeded epoch order (``state.shard_order``) and takes
the strided slice ``order[host_index::host_count]`` — disjoint coverage whose
union is exactly the dataset, once per epoch (asserted by the determinism
suite in ``tests/test_data.py``).

Reads honor the resilience + fault-injection planes: each physical read
consults ``core.faults.active_fault_plan().on_read(target)`` and retries
transient ``OSError``/``TimeoutError`` failures under a
``core.resilience.RetryPolicy``, counting retries on
``resilience_measures("data")``.

``MemorySource`` wraps an in-memory ``DataFrame`` or column dict in the same
interface so every existing call site (``fit_arrays`` and friends) rides the
one streaming plane unchanged.
"""

from __future__ import annotations

import dataclasses
import io as _io
import json as _json
import os
import time
from typing import Any, Callable, Sequence

import numpy as np

from ..core.resilience import RetryPolicy, resilience_measures

__all__ = ["Shard", "ShardedSource", "MemorySource", "default_read_retry",
           "resolve_host"]


def resolve_host(host_index: int | None,
                 host_count: int | None) -> tuple[int, int]:
    """The ONE place per-host striding resolves its jax process-topology
    defaults + validation — shared by ``DataLoader`` and the scoring
    planner so the two planes' shard assignment can never drift."""
    if host_index is None or host_count is None:
        import jax

        host_index = jax.process_index() if host_index is None else host_index
        host_count = jax.process_count() if host_count is None else host_count
    host_index, host_count = int(host_index), int(host_count)
    if not 0 <= host_index < host_count:
        raise ValueError(f"host_index {host_index} outside [0, {host_count})")
    return host_index, host_count

DEFAULT_SHARD_BYTES = 64 << 20
DEFAULT_SHARD_ROWS = 65536


def default_read_retry() -> RetryPolicy:
    """Shard reads hit network filesystems in production; transient failures
    retry on a short jittered schedule by default."""
    return RetryPolicy(backoffs_ms=(50, 200, 500))


@dataclasses.dataclass(frozen=True)
class Shard:
    """One independently readable slice of a dataset.

    ``kind`` selects the reader; ``start``/``stop`` are byte offsets for
    tabular files, row offsets for ``npy``/``memory`` shards, and listing
    offsets for image shards."""

    index: int
    kind: str            # jsonl | csv | npy | image | memory
    path: str            # file path ('' for memory shards)
    start: int
    stop: int

    @property
    def target(self) -> str:
        """The fault-plan / span match target."""
        return f"{self.path}[{self.start}:{self.stop}]"


def _line_aligned_ranges(size: int, shard_bytes: int, origin: int = 0
                         ) -> list[tuple[int, int]]:
    """Byte ranges covering [origin, size); a LINE belongs to the range that
    contains its first byte, so ranges need no alignment up front — the
    reader seeks and skips the partial first line itself."""
    shard_bytes = max(int(shard_bytes), 1)
    out = []
    pos = origin
    while pos < size:
        out.append((pos, min(pos + shard_bytes, size)))
        pos += shard_bytes
    return out  # empty when the file holds no body bytes (e.g. header-only)


def _read_lines_in_range(path: str, start: int, stop: int,
                         at_line_start: bool = False) -> list[bytes]:
    """The byte-range line reader shared by the jsonl and csv shards: every
    line whose first byte lands in [start, stop) belongs to this shard.
    ``at_line_start`` marks ``start`` as a known line boundary (byte 0, or
    the csv body origin right after the header) — no partial-line skip."""
    out = []
    with open(path, "rb") as f:
        f.seek(start)
        if start > 0 and not at_line_start:
            # Position to the first line STARTING in-range: back up one byte
            # and consume to the next newline — when byte start-1 is itself
            # a newline this is a no-op skip (the line beginning exactly at
            # ``start`` belongs to THIS shard and must not be dropped).
            f.seek(start - 1)
            f.readline()
        while True:
            line_start = f.tell()
            if line_start >= stop:
                break
            line = f.readline()
            if not line:
                break
            if line.strip():
                out.append(line)
    return out


def _columnar(rows: list[dict]) -> dict[str, np.ndarray]:
    """rows -> columnar dict over the union of keys (missing fields None),
    matching ``io.files.read_jsonl`` semantics."""
    keys: list = []
    for r in rows:
        keys += [k for k in r if k not in keys]
    from ..core.dataframe import _as_column

    n = len(rows)
    return {k: _as_column([r.get(k) for r in rows], n) for k in keys}


class ShardedSource:
    """A dataset as independently readable shards (see module docstring).

    Build with the classmethod constructors — :meth:`jsonl`, :meth:`csv`,
    :meth:`npy`, :meth:`image_dir` — or wrap in-memory data with
    :class:`MemorySource`.
    """

    kind = "sharded"

    def __init__(self, shards: Sequence[Shard],
                 reader: Callable[[Shard], dict],
                 retry_policy: RetryPolicy | None = None,
                 name: str = "source"):
        if not shards:
            raise ValueError("a ShardedSource needs at least one shard")
        self._shards = list(shards)
        self._reader = reader
        self.retry_policy = retry_policy or default_read_retry()
        self.name = name

    # -- interface ----------------------------------------------------------
    @property
    def num_shards(self) -> int:
        return len(self._shards)

    def shards(self) -> list[Shard]:
        return list(self._shards)

    def read_shard(self, shard: Shard | int) -> dict[str, np.ndarray]:
        """Materialize one shard as a columnar dict. Fault-injectable
        (``FaultSpec(..., planes=("data",))``) and retried under the
        source's ``RetryPolicy``."""
        if isinstance(shard, int):
            shard = self._shards[shard]
        return self._guarded(lambda: self._reader(shard), shard.target)

    def iter_shards(self):
        """Sequential (unshuffled) pass over every shard — the fixed-memory
        scan the streamed GBDT passes and stats accumulators use."""
        for s in self._shards:
            yield s, self.read_shard(s)

    def estimate_rows(self, sample_bytes: int = 1 << 20,
                      read_fallback: bool = True) -> int:
        """Cheap row-count estimate — progress %/ETA without a full
        pre-scan. Row-range shard kinds (npy/memory/image) answer exactly
        from shard metadata; byte-range kinds (jsonl/csv) sample up to
        ``sample_bytes`` from the first shard's file to get a bytes/row
        ratio and scale it over the total sharded byte count. Unknown custom
        readers fall back to reading ONE shard and scaling by shard count —
        ``read_fallback=False`` raises instead (the scoring runner passes
        it: a progress gauge must not cost a full shard read on remote
        storage). Memoized; an exact ``total_rows`` computed earlier is
        preferred."""
        if hasattr(self, "_total_rows"):
            return self._total_rows
        if hasattr(self, "_estimated_rows"):
            return self._estimated_rows
        if all(s.kind in ("npy", "memory") for s in self._shards):
            # start/stop are row offsets: exact.
            return self.total_rows()
        if all(s.kind in ("npy", "memory", "image") for s in self._shards):
            # image start/stop are file-LISTING offsets: one row per file
            # counted without decoding, so undecodable files the reader
            # drops (drop_invalid) overcount slightly — fine for an
            # estimate; exactness is total_rows()'s read pass
            est = sum(s.stop - s.start for s in self._shards)
            self._estimated_rows = est
            return est
        if all(s.kind in ("jsonl", "csv") for s in self._shards):
            first = self._shards[0]
            with open(first.path, "rb") as f:
                f.seek(first.start)
                buf = f.read(max(int(sample_bytes), 1))
            cut = buf.rfind(b"\n")
            sample = buf if cut < 0 else buf[:cut + 1]
            n_lines = max(sum(1 for ln in sample.splitlines() if ln.strip()),
                          1)
            bytes_per_row = max(len(sample), 1) / n_lines
            total_bytes = sum(s.stop - s.start for s in self._shards)
            est = max(int(round(total_bytes / bytes_per_row)), 1)
        else:
            if not read_fallback:
                raise ValueError(
                    "estimate_rows for custom shard kinds needs a full "
                    "shard read; call with read_fallback=True to allow it")
            est = _n_rows(self.read_shard(self._shards[0])) * self.num_shards
        self._estimated_rows = est
        return est

    def total_rows(self) -> int:
        """Total EXACT row count. Row-range shard kinds (npy/memory)
        answer from shard metadata alone; everything else — including
        image dirs, whose reader drops undecodable files so the listing
        count can overshoot — needs ONE full read pass. Memoized, but on a
        huge remote corpus prefer tracking counts as the loader discovers
        them (``IteratorState.shard_counts``) instead of calling this up
        front; for a cheap approximation use :meth:`estimate_rows`."""
        if not hasattr(self, "_total_rows"):
            if all(s.kind in ("npy", "memory")
                   for s in self._shards):
                self._total_rows = sum(s.stop - s.start for s in self._shards)
            else:
                self._total_rows = sum(
                    _n_rows(cols) for _, cols in self.iter_shards())
        return self._total_rows

    # -- read guard ---------------------------------------------------------
    def _guarded(self, fn: Callable[[], dict], target: str) -> dict:
        from ..core.faults import active_fault_plan

        policy = self.retry_policy
        measures = resilience_measures("data")
        for attempt in range(policy.max_attempts):
            try:
                plan = active_fault_plan()
                if plan is not None:
                    plan.on_read(target)
                out = fn()
                policy.on_success(first_attempt=attempt == 0)
                return out
            except (OSError, TimeoutError):
                if attempt + 1 >= policy.max_attempts \
                        or not policy.acquire_retry():
                    raise
                measures.count("retry")
                time.sleep(policy.backoff_ms(attempt) / 1000.0)
        raise AssertionError("unreachable")

    # -- constructors -------------------------------------------------------
    @classmethod
    def jsonl(cls, path, shard_bytes: int = DEFAULT_SHARD_BYTES,
              retry_policy: RetryPolicy | None = None) -> "ShardedSource":
        """JSON-lines file(s)/glob/dir — or an explicit LIST of file paths
        (what ``continual.logged_request_source`` passes for its
        DONE-committed parts) -> byte-range shards. Heterogeneous records
        union over all keys seen in the shard (like
        ``io.files.read_jsonl``)."""
        paths = _tabular_paths(path, "JSONL")
        shards, idx = [], 0
        for p in paths:
            for start, stop in _line_aligned_ranges(os.path.getsize(p),
                                                    shard_bytes):
                shards.append(Shard(idx, "jsonl", p, start, stop))
                idx += 1
        if not shards:
            raise ValueError(f"no data rows under {path!r} (the matched "
                             "JSONL files are all empty)")

        def read(shard: Shard) -> dict:
            from ..io.files import loads_jsonl_line

            # line numbers are unknowable inside a byte range without a
            # scan from byte 0 — the error names the shard's byte window
            # plus the line's ordinal within it instead
            rows = [loads_jsonl_line(ln, f"{shard.path}[{shard.start}:"
                                     f"{shard.stop}] line", k + 1)
                    for k, ln in enumerate(_read_lines_in_range(
                        shard.path, shard.start, shard.stop))]
            return _columnar(rows)

        return cls(shards, read, retry_policy, name="jsonl")

    @classmethod
    def csv(cls, path: str, shard_bytes: int = DEFAULT_SHARD_BYTES,
            retry_policy: RetryPolicy | None = None,
            **pandas_kw) -> "ShardedSource":
        """CSV file(s)/glob/dir -> byte-range shards; every shard re-reads
        the file's header line so any byte range parses standalone."""
        paths = _tabular_paths(path, "CSV")
        shards, idx, headers = [], 0, {}
        for p in paths:
            with open(p, "rb") as f:
                headers[p] = f.readline()
            body = len(headers[p])
            for start, stop in _line_aligned_ranges(os.path.getsize(p),
                                                    shard_bytes, origin=body):
                shards.append(Shard(idx, "csv", p, start, stop))
                idx += 1
        if not shards:
            raise ValueError(f"no data rows under {path!r} (the matched "
                             "CSV files hold headers only)")

        def read(shard: Shard) -> dict:
            import pandas as pd

            lines = _read_lines_in_range(
                shard.path, shard.start, shard.stop,
                at_line_start=shard.start == len(headers[shard.path]))
            body = b"".join(lines)
            whole_file = (shard.start == len(headers[shard.path])
                          and shard.stop >= os.path.getsize(shard.path))
            # per-LINE parity, not whole-shard: a slice torn inside quoted
            # fields at BOTH ends has even total quotes but its first and
            # last fragment lines are each odd
            if not whole_file and any(ln.count(b'"') % 2 for ln in lines):
                # byte-range splitting assumes one record per physical line
                # (the Spark splittable-CSV contract); an odd quote count in
                # a strict slice of the file means a quoted field with an
                # embedded newline (or a bare literal quote) straddles a
                # shard boundary — fail LOUD instead of feeding a torn
                # record fragment into training as a spurious row. A shard
                # covering the whole file can hold no torn record, so it
                # skips this check (lone literal quotes stay parseable).
                raise ValueError(
                    f"CSV shard {shard.target} cuts through a quoted "
                    "multi-line field (or the file holds bare literal "
                    "quotes); byte-range sharding needs one record per "
                    "line — raise shard_bytes past the file size (one "
                    "shard per file) or flatten embedded newlines")
            pdf = pd.read_csv(_io.BytesIO(headers[shard.path] + body),
                              **pandas_kw)
            return {c: pdf[c].to_numpy() for c in pdf.columns}

        return cls(shards, read, retry_policy, name="csv")

    @classmethod
    def npy(cls, path: str, column: str = "features",
            shard_rows: int = DEFAULT_SHARD_ROWS,
            retry_policy: RetryPolicy | None = None) -> "ShardedSource":
        """``.npy`` file(s)/glob/dir -> row-range shards (mmap metadata only
        at build time; each shard materializes its own row slice)."""
        from ..io.files import resolve_input_paths

        paths = resolve_input_paths(path, ".npy", exts=(".npy",))
        shards, idx = [], 0
        for p in paths:
            n = np.load(p, mmap_mode="r").shape[0]
            for start in range(0, n, max(int(shard_rows), 1)):
                shards.append(Shard(idx, "npy", p, start,
                                    min(start + shard_rows, n)))
                idx += 1

        def read(shard: Shard) -> dict:
            mm = np.load(shard.path, mmap_mode="r")
            return {column: np.asarray(mm[shard.start:shard.stop])}

        return cls(shards, read, retry_policy, name="npy")

    @classmethod
    def image_dir(cls, path: str, recursive: bool = True,
                  shard_files: int = 256, drop_invalid: bool = True,
                  retry_policy: RetryPolicy | None = None) -> "ShardedSource":
        """Image directory -> shards of ``shard_files`` files each, decoded
        to the ``io.files.read_image_files`` schema (path/image/height/
        width/channels)."""
        from ..io.files import _IMAGE_EXTS, _resolve_paths, decode_image_bytes

        files = _resolve_paths(path, recursive, _IMAGE_EXTS)
        if not files:
            raise FileNotFoundError(f"no image files under {path!r}")
        shard_files = max(int(shard_files), 1)
        shards = [Shard(i, "image", path, s, min(s + shard_files, len(files)))
                  for i, s in enumerate(range(0, len(files), shard_files))]

        def read(shard: Shard) -> dict:
            rows = []
            for p in files[shard.start:shard.stop]:
                with open(p, "rb") as f:
                    data = f.read()
                try:
                    arr = decode_image_bytes(data)
                except Exception:
                    if drop_invalid:
                        continue
                    rows.append({"path": os.path.abspath(p), "image": None,
                                 "height": 0, "width": 0, "channels": 0})
                    continue
                rows.append({"path": os.path.abspath(p), "image": arr,
                             "height": arr.shape[0], "width": arr.shape[1],
                             "channels": arr.shape[2]})
            return _columnar(rows)

        return cls(shards, read, retry_policy, name="image")


def _tabular_paths(path, what: str) -> list[str]:
    """``io.files.resolve_input_paths`` (the ONE resolver both planes list
    through) plus a streaming-only refinement: zero-byte files carry no
    shards, so they drop here — the eager readers instead keep them as
    empty partitions (the Spark file<->partition mapping). An explicit
    LIST of file paths bypasses globbing — the continual plane's request
    logger selects exactly its DONE-committed parts this way."""
    from ..io.files import resolve_input_paths

    if isinstance(path, (list, tuple)):
        missing = [p for p in path if not os.path.isfile(p)]
        if missing:
            raise FileNotFoundError(f"no such {what} file(s): {missing}")
        paths = [str(p) for p in path]
    else:
        paths = resolve_input_paths(path, what)
    return [p for p in paths if os.path.getsize(p) > 0]


def _n_rows(cols: dict) -> int:
    return len(next(iter(cols.values()))) if cols else 0


class MemorySource(ShardedSource):
    """In-memory data behind the sharded interface — every current call site
    (``fit_arrays``, DataFrame estimators) keeps working unchanged while
    riding the one streaming plane.

    Wraps a column dict or a ``core.DataFrame``. ``shard_rows=None`` keeps
    one shard per DataFrame partition (dicts become a single shard);
    passing ``shard_rows`` re-shards into fixed row windows — matching an
    on-disk layout row-for-row makes the loader's batch stream bit-identical
    to the on-disk source under the same seed (the equivalence the
    acceptance test asserts)."""

    def __init__(self, data: Any, shard_rows: int | None = None,
                 retry_policy: RetryPolicy | None = None):
        from ..core.dataframe import DataFrame

        if isinstance(data, DataFrame):
            parts = [dict(p) for p in data.partitions]
        else:
            parts = [dict(data)]
        if shard_rows is not None:
            whole = {k: np.concatenate([np.asarray(p[k]) for p in parts])
                     for k in parts[0]} if parts else {}
            n = _n_rows(whole)
            parts = [{k: v[s:s + shard_rows] for k, v in whole.items()}
                     for s in range(0, max(n, 1), max(int(shard_rows), 1))]
        self._parts = [p for p in parts if _n_rows(p) > 0] or parts[:1]
        shards = [Shard(i, "memory", "", 0, _n_rows(p))
                  for i, p in enumerate(self._parts)]

        def read(shard: Shard) -> dict:
            return dict(self._parts[shard.index])

        super().__init__(shards, read, retry_policy, name="memory")
