"""Checkpointable iterator state + the deterministic order functions.

The streaming plane's resume guarantee: the batch stream is a PURE FUNCTION
of ``(seed, epoch, shard layout, cursor)`` — no hidden RNG objects whose
bit-generator state would have to be serialized. Shard order for an epoch is
``shard_order(seed, epoch, ...)``; the row order inside a shard is
``row_order(seed, epoch, shard_index, ...)``. A mid-epoch checkpoint
therefore only needs FOUR cursors (epoch, rows emitted this epoch, global
batch count, per-shard row counts discovered so far) for the loader to
resume bit-identically: regenerate the epoch's orders, skip whole shards
whose cumulative row count fits under ``rows_emitted``, skip the remainder
inside the boundary shard, and continue — no replayed and no skipped rows.

``IteratorState.to_tree()`` is a plain numpy pytree, so it serializes
alongside the train state through ``parallel.checkpoint.AsyncCheckpointer``
(the ``data_iter`` subtree a ``fit_source`` checkpoint carries).
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["IteratorState", "ElasticPlan", "shard_order", "row_order"]


def shard_order(seed: int, epoch: int, n_shards: int,
                shuffle: bool = True) -> np.ndarray:
    """The epoch's global shard visit order (identical on every host; hosts
    then take strided disjoint slices of it)."""
    if not shuffle:
        return np.arange(n_shards, dtype=np.int64)
    return np.random.default_rng([int(seed), int(epoch), 0x5AD5]).permutation(
        n_shards).astype(np.int64)


def _window_shuffle(n: int, window: int, rng: np.random.Generator) -> np.ndarray:
    """Streaming window shuffle: a ``window``-slot buffer over the sequential
    row stream; each emit draws a random slot and refills it with the next
    row. Bounded shuffling locality (the out-of-core discipline) while still
    a pure function of the rng seed."""
    out = np.empty(n, dtype=np.int64)
    window = max(int(window), 1)
    buf = list(range(min(window, n)))
    nxt = len(buf)
    draws = rng.integers(0, window, size=n)  # one block of randomness up front
    for j in range(n):
        r = int(draws[j]) % len(buf)
        out[j] = buf[r]
        if nxt < n:
            buf[r] = nxt
            nxt += 1
        else:
            buf[r] = buf[-1]
            buf.pop()
    return out


def row_order(seed: int, epoch: int, shard_index: int, n_rows: int,
              mode: str = "full", window: int = 4096) -> np.ndarray:
    """Within-shard row visit order for one (seed, epoch, shard).

    ``mode``: 'full' — full permutation (shards are memory-bounded, so this
    is the default); 'window' — streaming window shuffle of locality
    ``window``; 'none' — sequential.
    """
    if n_rows <= 0:
        return np.empty(0, dtype=np.int64)
    if mode == "none":
        return np.arange(n_rows, dtype=np.int64)
    rng = np.random.default_rng([int(seed), int(epoch), int(shard_index),
                                 0x12D7])
    if mode == "full":
        return rng.permutation(n_rows).astype(np.int64)
    if mode == "window":
        return _window_shuffle(n_rows, window, rng)
    raise ValueError(f"shuffle_rows must be 'full', 'window' or 'none', "
                     f"got {mode!r}")


@dataclasses.dataclass
class IteratorState:
    """Where a :class:`~synapseml_tpu.data.loader.DataLoader` stands, as of
    the last EMITTED batch (prefetched-but-unconsumed work is excluded — a
    restore never replays or skips rows the training loop actually saw)."""

    epoch: int = 0
    rows_emitted: int = 0       # rows in emitted batches, current epoch, this host
    batches_emitted: int = 0    # global batch counter (across epochs)
    seed: int = 0
    # (n_shards,) row count per shard once discovered; -1 = not yet read.
    # Counts are a property of the SOURCE (identical every epoch), so a
    # resume can position inside the epoch without re-reading skipped shards.
    shard_counts: np.ndarray | None = None

    def copy(self) -> "IteratorState":
        return IteratorState(
            epoch=self.epoch, rows_emitted=self.rows_emitted,
            batches_emitted=self.batches_emitted, seed=self.seed,
            shard_counts=None if self.shard_counts is None
            else self.shard_counts.copy())

    def to_tree(self) -> dict:
        """Numpy-serializable pytree (rides inside checkpoint snapshots)."""
        return {
            "epoch": np.int64(self.epoch),
            "rows_emitted": np.int64(self.rows_emitted),
            "batches_emitted": np.int64(self.batches_emitted),
            "seed": np.int64(self.seed),
            "shard_counts": (np.asarray(self.shard_counts, np.int64)
                             if self.shard_counts is not None
                             else np.full(0, -1, np.int64)),
        }

    @classmethod
    def from_tree(cls, tree: dict) -> "IteratorState":
        counts = np.asarray(tree["shard_counts"], np.int64)
        return cls(epoch=int(tree["epoch"]),
                   rows_emitted=int(tree["rows_emitted"]),
                   batches_emitted=int(tree["batches_emitted"]),
                   seed=int(tree["seed"]),
                   shard_counts=counts if counts.size else None)


@dataclasses.dataclass
class ElasticPlan:
    """N→M elastic redistribution of a gang's per-host batch streams.

    The per-host stream partitioning is FROZEN at the gang's first launch:
    a run started on N hosts is, forever, N *virtual streams* (stream *s*
    reads shard slice ``order[s::N]`` — the exact per-host assignment a
    static N-host run uses, see :func:`shard_order`). Each virtual stream
    carries its own :class:`IteratorState` cursor; a coordinated
    checkpoint stores one cursor per stream (the writer's ``host_tree``).

    Resuming on M survivors multiplexes the N streams over M hosts —
    ``assignment(M)[j] = [j, j+M, j+2M, ...]`` — and each host round-robins
    its assigned streams, every stream continuing from ITS cursor. Because
    every row still flows through exactly the stream that owned it at
    launch, the union of emitted rows is exactly the dataset with **zero
    replayed and zero skipped rows**, for any N→M (including M=N: each
    host keeps one stream, i.e. the static layout).
    """

    orig_world: int
    states: list  # one IteratorState tree (``to_tree`` dict) per stream

    def __post_init__(self):
        if self.orig_world < 1:
            raise ValueError(f"orig_world must be >= 1, got {self.orig_world}")
        if len(self.states) != self.orig_world:
            raise ValueError(
                f"elastic plan needs one cursor per virtual stream: "
                f"{len(self.states)} state(s) for orig_world="
                f"{self.orig_world}")

    @classmethod
    def fresh(cls, world: int, seed: int) -> "ElasticPlan":
        return cls(orig_world=int(world),
                   states=[IteratorState(seed=int(seed)).to_tree()
                           for _ in range(int(world))])

    @classmethod
    def from_host_states(cls, orig_world: int, host_states: dict,
                         key: str = "data_iter") -> "ElasticPlan":
        """Rebuild the plan from a coordinated checkpoint's per-rank host
        payloads (``parallel.checkpoint.restore_host_states``). Each rank
        stored the cursors of the streams it was serving as
        ``{key: {stream_id: IteratorState tree}}``; their union must cover
        every virtual stream EXACTLY — a gap means a rank's shard
        vanished, and a cursor beyond ``orig_world`` means the caller's
        ``orig_world`` undercounts the run's frozen world (silently
        dropping it would skip that stream's remaining rows forever)."""
        states: dict[int, dict] = {}
        for rank, tree in host_states.items():
            cursors = tree.get(key) if isinstance(tree, dict) else None
            if cursors is None:
                continue
            for sid, st in cursors.items():
                states[int(sid)] = st
        missing = sorted(set(range(int(orig_world))) - set(states))
        if missing:
            raise ValueError(
                f"elastic resume is missing cursors for virtual stream(s) "
                f"{missing} (have {sorted(states)}) — the checkpoint does "
                f"not cover the original world of {orig_world}")
        extra = sorted(set(states) - set(range(int(orig_world))))
        if extra:
            raise ValueError(
                f"elastic resume found cursors for virtual stream(s) "
                f"{extra} beyond orig_world={orig_world} — the declared "
                f"original world undercounts the run's frozen world; "
                f"resuming would permanently skip those streams' rows")
        return cls(orig_world=int(orig_world),
                   states=[states[s] for s in range(int(orig_world))])

    def assignment(self, new_world: int) -> list[list[int]]:
        """Virtual streams per surviving host: strided, deterministic, and
        exhaustive — every stream lands on exactly one of the M hosts.
        M > N leaves hosts beyond N with an empty list; a training gang
        must clamp world to <= orig_world (an assignment-less member has
        no shard to ACK, so no checkpoint could ever commit)."""
        m = int(new_world)
        if m < 1:
            raise ValueError(f"new_world must be >= 1, got {m}")
        return [list(range(self.orig_world))[j::m] for j in range(m)]

