"""Streaming data plane: out-of-core sharded sources, async device
prefetch, and resumable epoch iterators.

The Spark-streaming role of the reference (executors feed file splits to the
compute engines) rebuilt TPU-natively:

* :mod:`.source` — :class:`ShardedSource` over jsonl/csv/npy/image dirs with
  byte-range shard splitting and per-host assignment aligned with the
  ``parallel/mesh`` process topology; :class:`MemorySource` wraps in-memory
  data so existing call sites ride the same plane.
* :mod:`.loader` — :class:`DataLoader`: deterministic seeded shard + row
  shuffles, batch assembly through the ``core/batching`` bucket ladder, and
  a bounded-queue background prefetcher with backpressure and full
  observability (``synapseml_data_*`` series, ``data.prefetch`` spans).
* :mod:`.state` — :class:`IteratorState`: checkpointable iterator cursors
  that serialize alongside ``parallel.checkpoint`` snapshots so a preempted
  job resumes mid-epoch bit-identically.

Training entry points: ``models.trainer.fit_source`` (and the thin
``fit_arrays`` wrapper), ``gbdt.train_booster_from_source``.
"""

from .loader import DataLoader, ElasticStreamSet  # noqa: F401
from .source import MemorySource, Shard, ShardedSource  # noqa: F401
from .state import (ElasticPlan, IteratorState, row_order,  # noqa: F401
                    shard_order)

__all__ = ["DataLoader", "ElasticStreamSet", "MemorySource", "Shard",
           "ShardedSource", "ElasticPlan", "IteratorState", "row_order",
           "shard_order"]
