"""Multi-model residency: one worker hosts N registry versions behind
per-model ``PipelineHolder`` slots with a byte-budgeted LRU.

The long tail of small models does not deserve a worker each — a real
model-serving fleet packs them onto shared capacity and evicts cold ones.
:class:`ResidencyManager` owns the slots: ``acquire(model)`` returns the
resident pipeline (touching LRU order) or loads it from the registry on a
miss, evicting least-recently-used residents until the artifact fits the
byte budget. Eviction rides the existing teardown machinery: the evicted
stage's executables leave the shared ``CompiledCache`` via
``release_executables`` (the PR-4 hot-swap discipline), any paged-KV engine
caches release their device page pools, and the model's AOT blob tier (when
loaded with ``use_aot``) detaches — a re-load either retraces or re-hits
the AOT blobs, visible in the compile-cache miss/aot-hit counters.

:func:`serve_multi_model` runs a :class:`~synapseml_tpu.io.serving.
ServingServer` whose serve loop routes each request row by the model path
segment (``POST /m/<model>/...``) to its resident slot — the worker-side
half of the ``RoutingFront``'s model-segment routing.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

import numpy as np

from ..core import batching as cb
from ..core import observability as obs
from ..core.dataframe import DataFrame

__all__ = ["ResidencyManager", "serve_multi_model", "model_path",
           "model_from_path", "artifact_nbytes"]

_RESIDENCY_METRICS = obs.HandleCache(lambda reg: {
    "resident_models": reg.gauge(
        "synapseml_fleet_resident_models",
        "models currently resident on this worker").labels(),
    "resident_bytes": reg.gauge(
        "synapseml_fleet_resident_bytes",
        "artifact bytes currently resident on this worker").labels(),
    "evictions": reg.counter(
        "synapseml_fleet_evictions_total",
        "residency LRU evictions", ("model",)),
    "loads": reg.counter(
        "synapseml_fleet_model_loads_total",
        "residency slot lookups", ("model", "outcome")),
})

# default warmup cap for a residency load (the PR-4 small-rung discipline:
# a miss-triggered load sits on a live request's critical path)
_RESIDENT_WARMUP_CAP = 16


def model_path(model: str) -> str:
    """The canonical request path for a model on a multi-model fleet."""
    return f"/m/{model}"


def model_from_path(path: str) -> str | None:
    """Extract the model segment from ``/m/<model>[/...][?query]``; None
    when the path does not address a model (health/admin/default
    traffic). Query/fragment suffixes are stripped — ``/m/x?k=v`` must
    route (and key admission/metrics) as ``x``, never as ``x?k=v``."""
    bare = str(path).split("?", 1)[0].split("#", 1)[0]
    parts = bare.split("/")
    if len(parts) >= 3 and parts[1] == "m" and parts[2]:
        return parts[2]
    return None


def artifact_nbytes(path: str) -> int:
    """Total bytes of a materialized artifact directory (the residency
    accounting unit: what evicting the model actually frees)."""
    total = 0
    for root, _dirs, files in os.walk(path):
        for name in files:
            try:
                total += os.path.getsize(os.path.join(root, name))
            except OSError:
                continue
    return total


def _teardown_stage(stage) -> None:
    """Release everything an evicted resident holds: cached executables
    (shared ``CompiledCache`` tokens) and any paged-KV engine page pools a
    causal-LM stage accumulated (``_cache_engines`` — the PR-6 donation
    buffers are device memory a dead resident must not pin)."""
    seen: set[int] = set()

    def walk(obj):
        if obj is None or id(obj) in seen:
            return
        seen.add(id(obj))
        engines = getattr(obj, "__dict__", {}).get("_cache_engines")
        if isinstance(engines, dict):
            for eng in list(engines.values()):
                try:
                    eng.abort_all()
                    eng.release()
                except Exception:  # noqa: BLE001 — teardown is best-effort
                    pass
            obj.__dict__.pop("_cache_engines", None)
        getter = getattr(obj, "get", None)
        if callable(getter):
            try:
                children = getter("stages")
            except Exception:  # noqa: BLE001 — not every stage has 'stages'
                children = None
            if isinstance(children, (list, tuple)):
                for child in children:
                    walk(child)

    walk(stage)
    cb.release_executables(stage)


class _Resident:
    __slots__ = ("holder", "version", "nbytes", "provider", "path")

    def __init__(self, holder, version, nbytes, provider, path):
        self.holder = holder
        self.version = version
        self.nbytes = nbytes
        self.provider = provider
        self.path = path


class ResidencyManager:
    """Byte-budgeted LRU of registry models resident in this process.

    ``registry`` is a :class:`~synapseml_tpu.registry.ModelRegistry` (or a
    root path/URL for one); ``refs`` maps model name -> the version/alias to
    resolve (default ``"latest"``). ``byte_budget`` bounds the summed
    artifact bytes; one artifact larger than the whole budget is refused
    outright. ``use_aot=True`` installs each resident's AOT blob tier so a
    residency miss re-load is I/O-bound, not compile-bound (falls back to
    JIT warmup on any blocker, mirroring ``/admin/load``)."""

    def __init__(self, registry, byte_budget: int,
                 refs: dict[str, str] | None = None,
                 default_ref: str = "latest",
                 use_aot: bool = False,
                 loop_cfg: dict | None = None,
                 warmup_cap: int = _RESIDENT_WARMUP_CAP,
                 nbytes_fn=None):
        if isinstance(registry, (str, os.PathLike)):
            from ..registry.registry import ModelRegistry

            registry = ModelRegistry(str(registry))
        self.registry = registry
        self.byte_budget = int(byte_budget)
        if self.byte_budget <= 0:
            raise ValueError(f"byte_budget must be > 0: {byte_budget}")
        self.refs = dict(refs or {})
        self.default_ref = default_ref
        self.use_aot = bool(use_aot)
        self.loop_cfg = dict(loop_cfg or
                             {"parse_json": True, "input_col": "body"})
        self.warmup_cap = int(warmup_cap)
        self._nbytes_fn = nbytes_fn or artifact_nbytes
        self._slots: "OrderedDict[str, _Resident]" = OrderedDict()
        self._lock = threading.RLock()
        # introspection reads a SNAPSHOT behind its own tiny lock: a miss
        # load holds the main lock for seconds (resolve + evict + warmup),
        # and /admin/stats — the autoscaler's queue-depth poll — must not
        # block on it (a stalled poll would blind the autoscaler exactly
        # while a cold-model load is building the backlog it should see)
        self._snap_lock = threading.Lock()
        self._snapshot: dict = {}
        self._snapshot_bytes = 0

    # -- introspection -----------------------------------------------------
    def resident(self) -> dict:
        """model -> {version, nbytes} (lock-free snapshot, refreshed on
        membership changes — order reflects loads/evictions, not
        per-request hit recency)."""
        with self._snap_lock:
            return dict(self._snapshot)

    def resident_bytes(self) -> int:
        with self._snap_lock:
            return self._snapshot_bytes

    def _refresh_snapshot(self) -> None:
        """(main lock held) Rebuild the introspection snapshot and export
        the occupancy gauges."""
        snap = {m: {"version": r.version, "nbytes": r.nbytes}
                for m, r in self._slots.items()}
        total = sum(r.nbytes for r in self._slots.values())
        with self._snap_lock:
            self._snapshot = snap
            self._snapshot_bytes = total
        m = _RESIDENCY_METRICS.get()
        m["resident_models"].set(len(snap))
        m["resident_bytes"].set(total)

    # -- the slot API ------------------------------------------------------
    def acquire(self, model: str):
        """(stage, version) for ``model``, loading on a miss and touching
        LRU order on a hit. Raises ``KeyError`` for a model the registry
        does not have and ``ValueError`` for one that cannot fit."""
        with self._lock:
            resident = self._slots.get(model)
            if resident is not None:
                # hit path (per request group): LRU touch only — the
                # snapshot refresh (O(slots) rebuild + gauge exports) runs
                # on MEMBERSHIP changes, not on every hit
                self._slots.move_to_end(model)
                _RESIDENCY_METRICS.get()["loads"].inc(model=model,
                                                      outcome="hit")
                return resident.holder.get()
            resident = self._load(model)
            # evict LRU-oldest only AFTER the newcomer loaded and warmed
            # successfully: a broken artifact must fail its own request,
            # never repeatedly tear down healthy neighbors (the cost is a
            # brief accounting overshoot while both exist)
            while sum(r.nbytes for r in self._slots.values()) \
                    + resident.nbytes > self.byte_budget:
                victim, old = next(iter(self._slots.items()))
                del self._slots[victim]
                self._teardown(victim, old)
            self._slots[model] = resident
            self._refresh_snapshot()
            _RESIDENCY_METRICS.get()["loads"].inc(model=model,
                                                  outcome="miss")
            return resident.holder.get()

    def evict(self, model: str) -> bool:
        """Release one resident (no-op False when absent)."""
        with self._lock:
            resident = self._slots.pop(model, None)
            if resident is None:
                return False
            self._teardown(model, resident)
            self._refresh_snapshot()
            return True

    def release_all(self) -> None:
        with self._lock:
            for model in list(self._slots):
                self.evict(model)

    # -- internals (lock held) ---------------------------------------------
    def _teardown(self, model: str, resident: _Resident) -> None:
        if resident.provider is not None:
            cb.get_compiled_cache().remove_aot_provider(resident.provider)
        _teardown_stage(resident.holder.pipeline)
        _RESIDENCY_METRICS.get()["evictions"].inc(model=model)

    def _load(self, model: str) -> _Resident:
        from ..io.serving import PipelineHolder, run_warmup
        from ..registry import aot as raot

        try:
            resolved = self.registry.resolve(
                model, self.refs.get(model, self.default_ref))
        except FileNotFoundError as e:
            raise KeyError(f"model {model!r} not in registry: {e}") from e
        nbytes = int(self._nbytes_fn(os.path.dirname(resolved.path)))
        if nbytes > self.byte_budget:
            raise ValueError(
                f"model {model!r} ({nbytes} bytes) exceeds the whole "
                f"residency budget ({self.byte_budget} bytes)")
        stage = resolved.stage
        provider = None
        aot_cfg = (resolved.manifest or {}).get("aot") or {}
        warmup_rows = (aot_cfg.get("warmup") or {}).get("rows") or []
        warmup_buckets = [b for b in cb.default_bucketer().ladder
                          if b <= self.warmup_cap]
        if self.use_aot and aot_cfg.get("entries"):
            blocker = raot.load_blocker(aot_cfg)
            if blocker is None:
                provider = raot.AOTExecutableSet(
                    aot_cfg,
                    os.path.join(os.path.dirname(resolved.path), "aot"))
                if provider.mechanism == "xla":
                    # zero-compile load: replay the manifest's full ladder
                    warmup_buckets = (aot_cfg.get("warmup") or {}) \
                        .get("buckets") or warmup_buckets
            else:
                raot.log_fallback(blocker, model=model,
                                  version=resolved.version)
        cache = cb.get_compiled_cache()
        if provider is not None:
            cache.install_aot_provider(provider)
            provider.begin_binding()
        try:
            if warmup_rows:
                run_warmup(stage, warmup_rows, warmup_buckets, self.loop_cfg)
        except Exception:
            if provider is not None:
                cache.remove_aot_provider(provider)
            cb.release_executables(stage)
            raise
        finally:
            if provider is not None:
                provider.freeze()
        return _Resident(PipelineHolder(stage, resolved.version),
                         resolved.version, nbytes, provider, resolved.path)


def serve_multi_model(residency: ResidencyManager, port: int = 0,
                      batch_interval_ms: int = 5,
                      latency_budget_ms: float | None = None,
                      max_batch_rows: int = 256,
                      reply_col: str = "reply",
                      version: str | None = None):
    """Serve every model the ``residency`` manager can resolve from ONE
    worker: requests address models by path segment (``POST /m/<name>``),
    the serve loop groups each drained micro-batch by model, acquires each
    group's resident pipeline (loading/evicting under the byte budget), and
    transforms the groups independently — one failing model's batch is that
    group's 500, never its neighbors'. Unknown models get terminal 404s.
    Returns the started :class:`~synapseml_tpu.io.serving.ServingServer`
    (``server.residency`` exposes the manager; ``/admin/stats`` reports the
    resident set)."""
    from ..io.serving import (PipelineHolder, ServingServer, _prepare_batch)

    server = ServingServer(port=port)
    # the holder slot holds the residency manager's identity for /admin
    # introspection; per-model holders live inside the manager
    server.pipeline_holder = PipelineHolder(residency, version)
    server.residency = residency
    server._loop_cfg = dict(residency.loop_cfg)
    server.start()
    budget_s = (batch_interval_ms if latency_budget_ms is None
                else latency_budget_ms) / 1000.0

    def loop():
        while server._running:
            batch = server.read_batch_adaptive(
                max_rows=max_batch_rows, latency_budget_s=budget_s,
                poll_timeout_s=max(batch_interval_ms, 10) / 1000.0)
            if batch.is_empty():
                continue
            # collect each column ONCE per drained batch; groups index into
            # the shared arrays (G resident models must not cost G full
            # re-materializations of the batch on the serving hot path)
            cols = {c: batch.collect_column(c)
                    for c in ("id", "method", "path", "body")}
            groups: dict[str | None, list[int]] = {}
            for i, p in enumerate(cols["path"]):
                groups.setdefault(model_from_path(p), []).append(i)
            for model, idxs in groups.items():
                _serve_group(cols, model, idxs)

    def _reply_rows(ids, idxs, payload, status) -> None:
        for i in idxs:
            ex = server.exchange_for(str(ids[i]))
            if ex is not None:
                ex.respond(payload, status=status)

    def _serve_group(cols, model, idxs) -> None:
        ids = cols["id"]
        if model is None:
            _reply_rows(ids, idxs, {"error": "multi-model worker: address "
                                             "a model as /m/<name>"}, 404)
            return
        try:
            stage, _v = residency.acquire(model)
        except (KeyError, ValueError) as e:
            _reply_rows(ids, idxs, {"error": str(e)}, 404)
            return
        except Exception as e:  # noqa: BLE001 — a failed LOAD (corrupt
            # artifact, warmup raise, blob I/O) is this model's 500; it
            # must never kill the serve thread and brick every neighbor
            _reply_rows(ids, idxs, {"error": f"model load failed: "
                                             f"{type(e).__name__}: {e}"},
                        500)
            return
        sub = DataFrame([{
            col: np.asarray([vals[i] for i in idxs], dtype=object)
            for col, vals in cols.items()
        }])
        try:
            prepared = _prepare_batch(sub, **residency.loop_cfg)
            server.reply_batch(stage.transform(prepared),
                               reply_col=reply_col)
        except Exception as e:  # noqa: BLE001 — one model's failure is
            _reply_rows(ids, idxs, {"error": str(e)}, 500)  # its own 500

    threading.Thread(target=loop, daemon=True).start()
    return server
