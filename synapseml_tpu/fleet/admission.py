"""Admission control for the routing front: token buckets, priority
classes, p99-budget load shedding.

The resilience plane's circuit breakers (PR 1) protect WORKERS — a dead
worker stops receiving traffic. This module protects SLOs: a model whose
offered load exceeds its declared budget sheds the excess AT THE FRONT with
``429 Too Many Requests`` + ``Retry-After``, before a request costs a
worker queue slot or a batch rung. Three rules, all per-model
(:class:`~synapseml_tpu.fleet.spec.AdmissionPolicy`):

* **token bucket** — ``rate_rps``/``burst`` bound sustained admission rate;
* **priority classes** — ``interactive`` > ``bulk``: bulk requests (the
  ``X-Priority: bulk`` header ``transform_source``-style clients send) may
  not spend the bucket below ``interactive_reserve × burst``, so bulk
  traffic can never starve interactive admission;
* **p99 shedding** — when the model's rolling p99 (fed by the front's
  per-request observations) blows ``p99_budget_ms``, incoming requests are
  shed NEWEST-first (the request being judged is the newest): bulk
  immediately, interactive only past ``hard_shed_factor`` × the budget.

Every decision lands in ``synapseml_fleet_admitted_total{model,priority}``
/ ``synapseml_fleet_shed_total{model,priority,reason}`` and in plain
monotonic counters (:meth:`AdmissionController.stats`) the acceptance tests
reconcile against client-observed outcomes.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable

from ..core import observability as obs
from .spec import AdmissionPolicy

__all__ = ["TokenBucket", "AdmissionDecision", "AdmissionController",
           "PRIORITIES", "priority_of"]

PRIORITIES = ("interactive", "bulk")

_ADMIT_METRICS = obs.HandleCache(lambda reg: {
    "admitted": reg.counter(
        "synapseml_fleet_admitted_total",
        "requests admitted by the fleet admission controller",
        ("model", "priority")),
    "shed": reg.counter(
        "synapseml_fleet_shed_total",
        "requests shed (429) by the fleet admission controller",
        ("model", "priority", "reason")),
})


def priority_of(headers) -> str:
    """Priority class of a request from its headers (``X-Priority: bulk``
    marks bulk-scoring traffic; everything else is interactive)."""
    try:
        v = headers.get("X-Priority") or headers.get("x-priority") or ""
    except AttributeError:
        return "interactive"
    return "bulk" if str(v).strip().lower() == "bulk" else "interactive"


class TokenBucket:
    """Monotonic-clock token bucket: ``burst`` capacity refilled at
    ``rate_per_s``. ``try_take(n, floor=f)`` spends only when at least
    ``f`` tokens would REMAIN — the priority-reserve primitive (bulk takes
    with ``floor = reserve × burst``, interactive with ``floor = 0``).
    ``clock`` is injectable so tests drive refills without sleeping."""

    def __init__(self, rate_per_s: float, burst: float,
                 clock: Callable[[], float] = time.monotonic):
        if rate_per_s <= 0 or burst <= 0:
            raise ValueError(f"rate_per_s and burst must be > 0: "
                             f"{rate_per_s}/{burst}")
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()
        self._lock = threading.Lock()

    def _refill(self, now: float) -> None:
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now

    @property
    def tokens(self) -> float:
        with self._lock:
            self._refill(self._clock())
            return self._tokens

    def try_take(self, n: float = 1.0, floor: float = 0.0) -> bool:
        with self._lock:
            self._refill(self._clock())
            if self._tokens - n < floor:
                return False
            self._tokens -= n
            return True

    def wait_time_s(self, n: float = 1.0, floor: float = 0.0) -> float:
        """Seconds until ``try_take(n, floor)`` could succeed (0 if now)."""
        with self._lock:
            self._refill(self._clock())
            deficit = (floor + n) - self._tokens
        return max(deficit / self.rate, 0.0)


@dataclasses.dataclass(frozen=True)
class AdmissionDecision:
    """The front's verdict on one request. ``retry_after_s`` feeds the
    HTTP ``Retry-After`` header on a shed (429) reply."""

    admitted: bool
    status: int = 200
    retry_after_s: float = 0.0
    reason: str = ""


_ADMITTED = AdmissionDecision(True)


class _ModelAdmission:
    """Per-model mutable state: the bucket, the latency window, counters."""

    __slots__ = ("policy", "bucket", "latencies", "counts", "lock",
                 "last_observed_at")

    def __init__(self, policy: AdmissionPolicy, clock):
        self.policy = policy
        self.bucket = (TokenBucket(policy.rate_rps, policy.burst, clock)
                       if policy.rate_rps else None)
        self.latencies = collections.deque(maxlen=int(policy.latency_window))
        self.last_observed_at: float | None = None
        # monotonic counters the tests reconcile against client outcomes
        self.counts = {(p, "admitted"): 0 for p in PRIORITIES}
        self.counts.update({(p, "shed"): 0 for p in PRIORITIES})
        self.lock = threading.Lock()

    def p99_ms(self) -> float | None:
        with self.lock:
            lat = sorted(self.latencies)
        if not lat:
            return None
        return lat[min(len(lat) - 1, int(len(lat) * 0.99))]


class AdmissionController:
    """Per-model admission decisions for a :class:`~synapseml_tpu.io.
    distributed_serving.RoutingFront` (installed via
    ``front.set_admission(controller)``; the front calls :meth:`admit`
    before routing and :meth:`observe` after each forwarded reply).

    ``policies`` maps model name -> :class:`AdmissionPolicy`; ``default``
    applies to models without an entry (``None`` = unknown models pass
    unthrottled). Build one from a spec with :meth:`from_spec`."""

    def __init__(self, policies: dict[str, AdmissionPolicy] | None = None,
                 default: AdmissionPolicy | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._default = default
        self._models: dict[str, _ModelAdmission] = {}
        self._lock = threading.Lock()
        for model, policy in (policies or {}).items():
            if policy is not None:
                self._models[model] = _ModelAdmission(policy, clock)

    @classmethod
    def from_spec(cls, spec, default: AdmissionPolicy | None = None,
                  clock: Callable[[], float] = time.monotonic
                  ) -> "AdmissionController":
        return cls(spec.admission_policies(), default=default, clock=clock)

    # default-policy state is created on demand from the CLIENT-controlled
    # model string — cap it so a path scanner cannot grow per-model buckets
    # / latency windows / metric labels forever (models past the cap share
    # one overflow state, which still rate-limits them collectively)
    _MAX_DEFAULT_MODELS = 512

    def _state(self, model: str) -> _ModelAdmission | None:
        state = self._models.get(model)
        if state is not None:
            return state
        if self._default is None:
            return None
        with self._lock:
            state = self._models.get(model)
            if state is None:
                if len(self._models) >= self._MAX_DEFAULT_MODELS:
                    state = self._models.get("_overflow")
                    if state is None:
                        state = self._models["_overflow"] = \
                            _ModelAdmission(self._default, self._clock)
                else:
                    state = self._models[model] = _ModelAdmission(
                        self._default, self._clock)
            return state

    # -- the decision ------------------------------------------------------
    def admit(self, model: str,
              priority: str = "interactive") -> AdmissionDecision:
        prio = "bulk" if str(priority).lower() == "bulk" else "interactive"
        state = self._state(model)
        m = _ADMIT_METRICS.get()
        if state is None:
            # no policy and no default: pass through UNCOUNTED — the model
            # string is client-controlled path data, and a counter label
            # per random probe would grow the metric family forever
            return _ADMITTED
        # the metric label is bounded the same way the state map is: a
        # model collapsed into the overflow slot must not mint a fresh
        # Prometheus label (registry children live forever)
        if model not in self._models:
            model = "_overflow"
        pol = state.policy
        decision = None
        # p99 budget first: shedding here is what keeps the SLO — a request
        # that would be admitted into an already-blown queue only deepens it
        if pol.p99_budget_ms:
            p99 = state.p99_ms()
            # shed requests never reach a worker, so they never feed the
            # latency window — without a probe, a once-blown p99 would shed
            # EVERYTHING forever. When no observation has landed within
            # retry_after_s, admit the request as a probe instead: its
            # latency refreshes the window and a recovered model reopens.
            now = self._clock()
            with state.lock:
                last = state.last_observed_at
                stale = last is None or now - last >= pol.retry_after_s
                if p99 is not None and p99 > pol.p99_budget_ms and stale:
                    # grant ONE probe per window: stamping the grant time
                    # makes the next retry_after_s non-stale, so a slow
                    # probe (latency >> retry_after_s) cannot open the
                    # gate to the whole offered load while it runs
                    state.last_observed_at = now
            if p99 is not None and p99 > pol.p99_budget_ms and not stale:
                if prio == "bulk" or \
                        p99 > pol.hard_shed_factor * pol.p99_budget_ms:
                    decision = AdmissionDecision(
                        False, 429, pol.retry_after_s, "p99_budget")
        if decision is None and state.bucket is not None:
            floor = (pol.interactive_reserve * state.bucket.burst
                     if prio == "bulk" else 0.0)
            if not state.bucket.try_take(1.0, floor=floor):
                decision = AdmissionDecision(
                    False, 429,
                    max(state.bucket.wait_time_s(1.0, floor=floor), 0.05),
                    "rate")
        if decision is None:
            decision = _ADMITTED
        verdict = "admitted" if decision.admitted else "shed"
        with state.lock:
            state.counts[(prio, verdict)] += 1
        if decision.admitted:
            m["admitted"].inc(model=model, priority=prio)
        else:
            m["shed"].inc(model=model, priority=prio,
                          reason=decision.reason)
        return decision

    def observe(self, model: str, latency_ms: float, ok: bool = True) -> None:
        """Feed one served request's latency into the model's p99 window
        (the front calls this after every forwarded reply). FAILED replies
        stamp the freshness clock but do NOT enter the window: a saturated
        fleet shedding fast queue-full 503s would otherwise fill the window
        with millisecond failure latencies, drop the computed p99 below
        budget, and reopen admission into the very overload being shed."""
        state = self._models.get(model)
        if state is None and self._default is not None:
            # a model folded into the overflow slot at admit() time must
            # feed the SAME state, or p99 shedding (and the probe clock)
            # would be silently inert for every over-cap model
            state = self._models.get("_overflow")
        if state is None:
            return
        with state.lock:
            if ok:
                state.latencies.append(float(latency_ms))
            state.last_observed_at = self._clock()

    # -- introspection -----------------------------------------------------
    def p99_ms(self, model: str) -> float | None:
        state = self._models.get(model)
        return state.p99_ms() if state is not None else None

    def stats(self) -> dict:
        """Per-model monotonic admitted/shed counters + current p99 — the
        reconciliation surface for tests and the autoscaler's shed signal."""
        out: dict = {}
        for model, state in list(self._models.items()):
            with state.lock:
                counts = dict(state.counts)
            out[model] = {
                "admitted": {p: counts[(p, "admitted")] for p in PRIORITIES},
                "shed": {p: counts[(p, "shed")] for p in PRIORITIES},
                "p99_ms": state.p99_ms(),
                "tokens": (round(state.bucket.tokens, 3)
                           if state.bucket is not None else None),
            }
        return out
