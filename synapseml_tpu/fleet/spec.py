"""Declarative fleet specification: per-model SLO targets + admission rules.

One :class:`FleetSpec` is the control plane's whole configuration — what the
autoscaler reconciles toward (``fleet/autoscaler.py``), what the admission
controller enforces (``fleet/admission.py``), and what the residency budget
bounds (``fleet/residency.py``). The spec is plain data with a JSON round
trip, so a fleet's desired state can live in version control next to the
model registry it points at (the same declarative discipline the sharding
plane's rule tables follow).
"""

from __future__ import annotations

import dataclasses
import json

__all__ = ["AdmissionPolicy", "ModelSLO", "FleetSpec"]


@dataclasses.dataclass
class AdmissionPolicy:
    """Per-model admission rules (``fleet/admission.py`` enforces them).

    * ``rate_rps``/``burst`` — a token bucket on the routing front; ``None``
      disables rate limiting. ``burst`` defaults to ``2 * rate_rps``.
    * ``interactive_reserve`` — the fraction of the bucket bulk traffic may
      never spend into: bulk requests are refused while fewer than
      ``reserve * burst`` tokens remain, so a bulk-scoring flood can never
      starve interactive traffic of admission capacity.
    * ``p99_budget_ms`` — the latency SLO the shedder protects: when the
      model's observed p99 exceeds it, incoming (NEWEST-first — the request
      being judged is the newest) bulk requests are shed with 429 +
      ``Retry-After``; interactive requests are shed only past
      ``hard_shed_factor`` × the budget (total overload).
    """

    rate_rps: float | None = None
    burst: float | None = None
    p99_budget_ms: float | None = None
    interactive_reserve: float = 0.2
    hard_shed_factor: float = 2.0
    retry_after_s: float = 1.0
    latency_window: int = 256

    def __post_init__(self):
        if self.burst is None and self.rate_rps is not None:
            self.burst = 2.0 * float(self.rate_rps)
        if not 0.0 <= float(self.interactive_reserve) < 1.0:
            raise ValueError(f"interactive_reserve must be in [0, 1): "
                             f"{self.interactive_reserve}")
        if float(self.hard_shed_factor) < 1.0:
            raise ValueError(f"hard_shed_factor must be >= 1: "
                             f"{self.hard_shed_factor}")
        if self.rate_rps is not None \
                and (1.0 - self.interactive_reserve) * self.burst < 1.0:
            # bulk needs a full token ABOVE the reserve floor; a config
            # where that can never happen silently blackholes bulk forever
            raise ValueError(
                f"(1 - interactive_reserve) * burst must be >= 1 or bulk "
                f"traffic can never be admitted: reserve="
                f"{self.interactive_reserve}, burst={self.burst} — raise "
                f"burst or lower the reserve")


@dataclasses.dataclass
class ModelSLO:
    """One model's serving targets — the autoscaler's reconcile input.

    ``model`` is the registry name; ``ref`` the version/alias spawned
    workers ``/admin/load``. Scale-up triggers when the mean per-worker
    queue depth exceeds ``target_queue_depth`` OR the model's routed p95
    exceeds ``p95_slo_ms``; scale-down needs ``scale_down_after``
    consecutive reconciles with MEASURED near-idle queues (<= 25% of
    target; p95 is deliberately not consulted — its rolling window decays
    too slowly to gate downs, and a no-signal pass never counts as idle) —
    asymmetric on purpose: up fast, down slow. ``serve`` holds
    per-model worker knobs passed to ``serve_pipeline`` (scheduler,
    ``batch_interval_ms``, ``max_batch_rows``, ...)."""

    model: str
    ref: str = "latest"
    min_workers: int = 1
    max_workers: int = 4
    target_queue_depth: float = 8.0
    p95_slo_ms: float | None = None
    scale_down_after: int = 3
    up_cooldown_s: float = 2.0
    down_cooldown_s: float = 10.0
    admission: AdmissionPolicy | None = None
    serve: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.min_workers < 0 or self.max_workers < 1 \
                or self.min_workers > self.max_workers:
            raise ValueError(
                f"{self.model}: need 0 <= min_workers <= max_workers "
                f"(>=1), got {self.min_workers}/{self.max_workers}")
        if isinstance(self.admission, dict):
            self.admission = AdmissionPolicy(**self.admission)


@dataclasses.dataclass
class FleetSpec:
    """The whole fleet's declared state: the models it serves (each a
    :class:`ModelSLO`), the reconcile cadence, and the per-worker residency
    byte budget for multi-model workers (``None`` = single-model workers,
    no eviction)."""

    models: list[ModelSLO]
    reconcile_interval_s: float = 1.0
    byte_budget: int | None = None

    def __post_init__(self):
        self.models = [m if isinstance(m, ModelSLO) else ModelSLO(**m)
                       for m in self.models]
        names = [m.model for m in self.models]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate model names in FleetSpec: {names}")

    def slo_for(self, model: str) -> ModelSLO | None:
        for m in self.models:
            if m.model == model:
                return m
        return None

    def admission_policies(self) -> dict[str, AdmissionPolicy]:
        return {m.model: m.admission for m in self.models
                if m.admission is not None}

    # -- JSON round trip (the spec lives in version control) ---------------
    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "FleetSpec":
        return cls(**json.loads(text))
