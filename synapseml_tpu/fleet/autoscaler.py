"""Elastic autoscaling: a reconcile loop spawning/draining serving workers
against per-model SLO targets.

The serving planes already emit every signal a control loop needs (PR-2
observability): worker queue depth (``GET /admin/stats``), routed p95 per
model (``RoutingFront.version_stats()``), shed rates (the admission
controller). :class:`FleetAutoscaler` closes the loop — each reconcile it
reaps dead workers, reads the signals, moves the per-model desired count
(up fast on queue/p95 pressure, down slowly after a sustained idle streak),
and converges the live set through a pluggable :class:`WorkerLauncher`:

* scale-UP workers ``/admin/load`` their registry ref with ``use_aot`` so a
  fresh worker maps in precompiled executable ladders instead of tracing
  (PR-9) — scale-up latency is process-start + I/O, not compile;
* scale-DOWN workers drain gracefully (``POST /admin/drain``): they stop
  accepting requests, finish the queued backlog with terminal replies,
  deregister from the :class:`~synapseml_tpu.io.distributed_serving.
  WorkerRegistry`, and exit — indistinguishable-from-crash removals are
  gone;
* a worker lost to a real crash is replaced within one reconcile interval
  (the chaos acceptance), with the front's per-worker breakers containing
  the blast radius in the meantime.

Two launchers ship: :class:`ThreadWorkerLauncher` (in-process servers on
real ports — cheap, for tests and single-host fleets) and
:class:`SubprocessWorkerLauncher` (one OS process per worker via
:func:`fleet_worker_main` — the bench/chaos configuration). Both register
workers over the registry's real HTTP surface so the front routes to a
scaled-up worker the moment it is ready.

Decisions and state export as ``synapseml_fleet_*`` series (desired/actual
workers, scale events, worker-seconds) and every reconcile runs under one
``fleet.reconcile`` span.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np

from ..core import observability as obs
from ..core.pipeline import Transformer
from .spec import FleetSpec, ModelSLO

__all__ = ["WorkerHandle", "WorkerLauncher", "ThreadWorkerLauncher",
           "SubprocessWorkerLauncher", "FleetAutoscaler", "FleetSignals",
           "fleet_worker_main"]

_FLEET_METRICS = obs.HandleCache(lambda reg: {
    "desired": reg.gauge(
        "synapseml_fleet_desired_workers",
        "autoscaler desired worker count", ("model",)),
    "actual": reg.gauge(
        "synapseml_fleet_actual_workers",
        "live (spawned, not drained) worker count", ("model",)),
    "scale_events": reg.counter(
        "synapseml_fleet_scale_events_total",
        "autoscaler scale decisions", ("model", "direction")),
    "worker_seconds": reg.counter(
        "synapseml_fleet_worker_seconds_total",
        "accumulated live worker-seconds (the fleet's cost integral)",
        ("model",)),
    "reconcile_ms": reg.histogram(
        "synapseml_fleet_reconcile_ms",
        "wall time of one reconcile pass").labels(),
})

_HANDLE_IDS = itertools.count(1)


def _post_json(url: str, payload: dict, timeout_s: float = 10.0) -> None:
    """The one JSON-POST helper every fleet HTTP hop uses (registration,
    drain) — the header/encoding/timeout contract lives in one place."""
    urllib.request.urlopen(urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"}),
        timeout=timeout_s).read()


class _PlaceholderStage(Transformer):
    """What a spawning worker serves for the instant before its
    ``/admin/load`` swap lands: every request gets a terminal 503-ish
    reply, never a hang."""

    def _transform(self, df):
        def per_part(p):
            out = dict(p)
            out["reply"] = np.asarray(
                [{"error": "worker still loading"}] * len(p["id"]),
                dtype=object)
            return out

        return df.map_partitions(per_part)


@dataclasses.dataclass
class WorkerHandle:
    """One launched worker as the autoscaler tracks it. ``token`` is
    launcher-private (the server object / the Popen)."""

    model: str
    token: object = None
    pid: int | None = None
    host: str | None = None
    port: int | None = None
    spawned_at: float = 0.0
    state: str = "starting"  # starting -> ready -> draining -> dead
    drain_at: float | None = None
    handle_id: int = dataclasses.field(
        default_factory=lambda: next(_HANDLE_IDS))

    @property
    def endpoint(self) -> str | None:
        if self.host is None or self.port is None:
            return None
        return f"http://{self.host}:{self.port}"


class WorkerLauncher:
    """The pluggable spawn/drain/kill surface the autoscaler drives.
    Implementations must make ``spawn`` non-blocking-ish (a worker may
    finish coming up after spawn returns; it counts as live meanwhile)."""

    def spawn(self, slo: ModelSLO) -> WorkerHandle:
        raise NotImplementedError

    def alive(self, handle: WorkerHandle) -> bool:
        raise NotImplementedError

    def drain(self, handle: WorkerHandle, timeout_s: float = 30.0) -> bool:
        """Ask the worker to drain gracefully; False when unreachable (the
        caller falls back to :meth:`kill`). The POST replies immediately
        (the backlog finishes asynchronously), so the HTTP timeout is kept
        SHORT — the autoscaler calls this under its lock, and a wedged
        victim must not stall introspection for long."""
        endpoint = handle.endpoint
        if endpoint is None:
            return False
        try:
            _post_json(endpoint + "/admin/drain",
                       {"timeout_s": timeout_s}, timeout_s=3.0)
            return True
        except (urllib.error.URLError, OSError):
            return False

    def kill(self, handle: WorkerHandle) -> None:
        raise NotImplementedError

    def reap(self, handle: WorkerHandle) -> None:
        """Post-death cleanup (process wait / socket close). Idempotent."""

    def close(self) -> None:
        """Tear down everything this launcher spawned."""


class ThreadWorkerLauncher(WorkerLauncher):
    """In-process workers: each ``spawn`` starts a real
    ``serve_pipeline`` HTTP server on its own port (own serve thread),
    ``/admin/load``s the model's registry ref, and registers with the
    driver's :class:`~synapseml_tpu.io.distributed_serving.WorkerRegistry`
    over HTTP — the full fleet surface without process-spawn cost. ``kill``
    closes the server socket abruptly (the crash the chaos tests inject);
    drained workers deregister and stop cleanly."""

    def __init__(self, registry_root: str, worker_registry,
                 use_aot: bool = False, warmup_rows: list | None = None,
                 serve_defaults: dict | None = None):
        self.registry_root = str(registry_root)
        self.worker_registry = worker_registry
        self.use_aot = bool(use_aot)
        self.warmup_rows = list(warmup_rows or [])
        self.serve_defaults = dict(serve_defaults or {})
        self._pids = itertools.count(-2, -1)  # fake, unique, never a real pid
        self._handles: list[WorkerHandle] = []

    def spawn(self, slo: ModelSLO) -> WorkerHandle:
        from ..io.serving import serve_pipeline

        kwargs = {"batch_interval_ms": 5, **self.serve_defaults,
                  **dict(slo.serve)}
        server = serve_pipeline(_PlaceholderStage(), version="starting",
                                **kwargs)
        payload = {"registry": self.registry_root, "model": slo.model,
                   "ref": slo.ref, "version": slo.model,
                   "aot": self.use_aot}
        if self.warmup_rows:
            payload["warmup"] = self.warmup_rows
        status, reply = server._admin_load(json.dumps(payload).encode())
        if status != 200:
            server.stop()
            raise RuntimeError(f"worker load of {slo.model}:{slo.ref} "
                               f"failed: {reply}")
        handle = WorkerHandle(model=slo.model, token=server,
                              pid=next(self._pids), host=server.host,
                              port=server.port,
                              spawned_at=time.monotonic(), state="ready")
        info = {"host": server.host, "port": server.port,
                "pid": handle.pid, "version": slo.model,
                "model": slo.model,
                "aot": (reply.get("warmup") or {}).get("mode")}
        register_url = self.worker_registry.address + "/register"

        def on_drained(_report):
            from ..io.distributed_serving import deregister_worker

            handle.state = "dead"
            deregister_worker(register_url, info)
            server.stop()

        server.on_drained = on_drained
        try:
            _post_json(register_url, info)
        except (urllib.error.URLError, OSError):
            # a failed registration must not leak a running, loaded server
            # the autoscaler can never reach (it would be in neither the
            # handle set nor the registry)
            server.stop()
            raise
        self._handles.append(handle)
        return handle

    def alive(self, handle: WorkerHandle) -> bool:
        server = handle.token
        return handle.state != "dead" and getattr(server, "_running", False)

    def kill(self, handle: WorkerHandle) -> None:
        """Abrupt crash: close the listening socket mid-flight, leaving the
        (now stale) registration for the breakers to discover."""
        handle.state = "dead"
        server = handle.token
        try:
            server.stop()
        except OSError:
            pass

    def reap(self, handle: WorkerHandle) -> None:
        if handle in self._handles:
            self._handles.remove(handle)

    def close(self) -> None:
        for handle in list(self._handles):
            self.kill(handle)
            self.reap(handle)


class SubprocessWorkerLauncher(WorkerLauncher):
    """One OS process per worker (:func:`fleet_worker_main`): the honest
    scale-up measurement — a spawned worker pays interpreter + jax init +
    registry resolve, and with ``use_aot`` maps in the published executable
    ladder instead of tracing (PR-9 zero-cold-start). The worker registers
    itself; the autoscaler backfills host/port from the registry table when
    the registration lands."""

    def __init__(self, registry_root: str, worker_registry,
                 use_aot: bool = True, warmup_rows: list | None = None,
                 serve_defaults: dict | None = None,
                 env: dict | None = None,
                 extra_sys_path: tuple = ()):
        self.registry_root = str(registry_root)
        self.worker_registry = worker_registry
        self.use_aot = bool(use_aot)
        self.warmup_rows = list(warmup_rows or [])
        self.serve_defaults = dict(serve_defaults or {})
        self._env = dict(env or {})
        self._extra_sys_path = tuple(extra_sys_path)
        self._procs: list[subprocess.Popen] = []

    def spawn(self, slo: ModelSLO) -> WorkerHandle:
        register_url = self.worker_registry.address + "/register"
        kwargs = {"batch_interval_ms": 5, **self.serve_defaults,
                  **dict(slo.serve)}
        code = (
            "from synapseml_tpu.fleet.autoscaler import fleet_worker_main; "
            f"fleet_worker_main({self.registry_root!r}, {slo.model!r}, "
            f"{slo.ref!r}, {register_url!r}, serve_kwargs={kwargs!r}, "
            f"use_aot={self.use_aot!r}, warmup_rows={self.warmup_rows!r})")
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.update(self._env)
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        paths = [repo_root, *self._extra_sys_path]
        env["PYTHONPATH"] = os.pathsep.join(
            [*paths, env.get("PYTHONPATH", "")])
        proc = subprocess.Popen([sys.executable, "-c", code], env=env)
        self._procs.append(proc)
        return WorkerHandle(model=slo.model, token=proc, pid=proc.pid,
                            spawned_at=time.monotonic())

    def alive(self, handle: WorkerHandle) -> bool:
        return handle.token.poll() is None

    def kill(self, handle: WorkerHandle) -> None:
        handle.state = "dead"
        handle.token.kill()

    def reap(self, handle: WorkerHandle) -> None:
        proc = handle.token
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
        if proc in self._procs:
            self._procs.remove(proc)

    def close(self) -> None:
        for proc in list(self._procs):
            proc.terminate()
        for proc in list(self._procs):
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        self._procs.clear()


def fleet_worker_main(registry_root: str, model: str, ref: str = "latest",
                      register_url: str | None = None,
                      serve_kwargs: dict | None = None,
                      use_aot: bool = True,
                      warmup_rows: list | None = None,
                      version: str | None = None) -> None:
    """Fleet worker process entry: serve a placeholder, ``/admin/load`` the
    registry ref (``use_aot=True`` rides the PR-9 zero-cold-start path —
    the swap report in the registration shows whether it did), register
    with the driver, and park. ``POST /admin/drain`` finishes the backlog,
    deregisters, and exits the process — the graceful half of elasticity."""
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    from ..io.serving import serve_pipeline

    server = serve_pipeline(_PlaceholderStage(), version="starting",
                            **(serve_kwargs or {}))
    payload = {"registry": registry_root, "model": model, "ref": ref,
               "version": version or model, "aot": bool(use_aot)}
    if warmup_rows:
        payload["warmup"] = list(warmup_rows)
    status, reply = server._admin_load(json.dumps(payload).encode())
    if status != 200:
        print(f"fleet worker load failed ({status}): {reply}", flush=True)
        raise SystemExit(1)
    info = {"host": server.host, "port": server.port, "pid": os.getpid(),
            "version": version or model, "model": model,
            "aot": (reply.get("warmup") or {}).get("mode")}
    if register_url:
        def on_drained(_report):
            from ..io.distributed_serving import deregister_worker

            deregister_worker(register_url, info)
            # sys.exit would only end the drain thread; the park loop below
            # holds the process — a drained worker must actually go away
            os._exit(0)

        server.on_drained = on_drained
        _post_json(register_url, info, timeout_s=30.0)
    print(f"fleet worker ready {info}", flush=True)
    while True:  # killed by the launcher, or exits via on_drained
        time.sleep(1.0)


@dataclasses.dataclass
class FleetSignals:
    """One model's observed load, as one reconcile pass read it."""

    queue_per_worker: float | None = None  # mean /admin/stats queue depth
    p95_ms: float | None = None            # routed p95 (version_stats)
    workers_polled: int = 0
    # mean engine prefix-cache hit rate across polled workers (LLM fleets
    # with ``prefix_cache`` on; None elsewhere) — the
    # ``synapseml_llm_prefix_hit_rate`` series as /admin/stats exposes it.
    # Observability for now: a high hit rate means routed stickiness is
    # working and effective per-worker capacity is above the cold number.
    prefix_hit_rate: float | None = None


class _ModelState:
    __slots__ = ("desired", "underload_streak", "last_up_at", "last_down_at")

    def __init__(self, desired: int):
        self.desired = desired
        self.underload_streak = 0
        self.last_up_at = float("-inf")
        self.last_down_at = float("-inf")


class FleetAutoscaler:
    """The reconcile loop over a :class:`~synapseml_tpu.fleet.spec.
    FleetSpec`: every ``spec.reconcile_interval_s`` it reaps the dead,
    reads the signals, adjusts per-model desired counts, and converges the
    fleet through the launcher. ``front`` (a ``RoutingFront``) supplies
    routed p95 per model; ``worker_registry`` is the driver-side
    registration table dead workers are pruned from. ``signals_fn`` is
    injectable for deterministic tests (``(slo, live_handles) ->
    FleetSignals``); the default polls each worker's ``/admin/stats``.

    Scale policy (per model, all knobs on the :class:`ModelSLO`):

    * **up** — overloaded (queue/worker > ``target_queue_depth`` OR p95 >
      ``p95_slo_ms``) and past ``up_cooldown_s`` since the last up: desired
      doubles (clamped to ``max_workers``) — load steps are exponential,
      so the response is too;
    * **down** — ``scale_down_after`` consecutive reconciles with the queue
      near-idle (<= 25% of target) and past ``down_cooldown_s``: desired
      drops by ONE (drain the newest worker) — down is deliberately linear
      and slow, a flapping fleet is worse than a briefly oversized one;
    * **replace** — live < desired for any reason (crash, OOM, kill -9):
      spawned back within the SAME reconcile pass.
    """

    def __init__(self, spec: FleetSpec, launcher: WorkerLauncher,
                 front=None, worker_registry=None,
                 signals_fn=None, clock=time.monotonic,
                 stats_timeout_s: float = 2.0,
                 drain_timeout_s: float = 30.0):
        self.spec = spec
        self.launcher = launcher
        self.front = front
        self.worker_registry = worker_registry
        self.clock = clock
        self.stats_timeout_s = float(stats_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self._signals_fn = signals_fn or self._default_signals
        self._handles: dict[str, list[WorkerHandle]] = {
            slo.model: [] for slo in spec.models}
        self._draining: list[WorkerHandle] = []
        self._state: dict[str, _ModelState] = {
            slo.model: _ModelState(slo.min_workers) for slo in spec.models}
        self._last_reconcile_at: float | None = None
        self.worker_seconds: dict[str, float] = {
            slo.model: 0.0 for slo in spec.models}
        self.events: list[dict] = []
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- introspection -----------------------------------------------------
    def live_handles(self, model: str) -> list[WorkerHandle]:
        with self._lock:
            return [h for h in self._handles.get(model, ())
                    if self.launcher.alive(h)]

    def actual(self, model: str) -> int:
        return len(self.live_handles(model))

    def desired(self, model: str) -> int:
        with self._lock:
            return self._state[model].desired

    def wait_ready(self, model: str, n: int, timeout_s: float = 60.0) -> None:
        """Block until ``n`` workers of ``model`` are REGISTERED (routable),
        not merely spawned — the scale-up completion point."""
        if self.worker_registry is None:
            raise RuntimeError("wait_ready needs a worker_registry")
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            got = sum(1 for w in self.worker_registry.workers()
                      if w.get("model") == model)
            if got >= n:
                return
            time.sleep(0.05)
        raise TimeoutError(f"{n} worker(s) of {model!r} not registered "
                           f"within {timeout_s}s")

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "FleetAutoscaler":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self, drain: bool = False) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        with self._lock:
            handles = [h for hs in self._handles.values() for h in hs]
            handles += list(self._draining)
        draining: list[WorkerHandle] = []
        for h in handles:
            if drain and self.launcher.alive(h):
                self._backfill_endpoints(h.model, [h])
                if self.launcher.drain(h, timeout_s=self.drain_timeout_s):
                    draining.append(h)  # reap only AFTER the drain window
                else:
                    self.launcher.kill(h)
            elif self.launcher.alive(h):
                self.launcher.kill(h)
            if h not in draining:
                self.launcher.reap(h)
        if draining:
            # a drain POST returns immediately; the worker finishes its
            # backlog asynchronously for up to drain_timeout_s — reaping
            # (which escalates to SIGKILL) before that window closes would
            # abandon the very exchanges the drain promised to finish
            deadline = time.monotonic() + self.drain_timeout_s + 5.0
            while time.monotonic() < deadline and \
                    any(self.launcher.alive(h) for h in draining):
                time.sleep(0.05)
            for h in draining:
                if self.launcher.alive(h):
                    self.launcher.kill(h)
                self.launcher.reap(h)
        with self._lock:
            for hs in self._handles.values():
                hs.clear()
            self._draining.clear()
        # belt-and-suspenders for the join-timeout race: if an in-flight
        # reconcile pass outlived the join and spawned after the snapshot
        # above, the launcher still owns every worker it ever started
        self.launcher.close()

    def _run(self) -> None:
        while not self._stop.wait(self.spec.reconcile_interval_s):
            try:
                self.reconcile_once()
            except Exception:  # noqa: BLE001 — the loop must survive a bad
                pass           # signal read; the next tick retries

    # -- signals -----------------------------------------------------------
    def _default_signals(self, slo: ModelSLO,
                         live: list[WorkerHandle]) -> FleetSignals:
        self._backfill_endpoints(slo.model, live)
        depths = []
        hit_rates = []
        for h in live:
            if h.endpoint is None:
                continue
            try:
                with urllib.request.urlopen(
                        h.endpoint + "/admin/stats",
                        timeout=self.stats_timeout_s) as r:
                    stats = json.loads(r.read())
                depths.append(float(stats.get("queue_depth", 0)))
                h.state = "ready"
            except (urllib.error.URLError, OSError, ValueError):
                continue  # unreachable mid-poll: the breaker plane's job
            # LLM workers surface engine stats under "llm" (serve_llm sets
            # server.llm_stats_fn); absent/odd shapes just skip the signal
            try:
                rate = ((stats.get("llm") or {}).get("prefix_cache")
                        or {}).get("hit_rate")
                if rate is not None:
                    hit_rates.append(float(rate))
            except (AttributeError, TypeError, ValueError):
                pass
        p95 = None
        if self.front is not None:
            p95 = (self.front.version_stats().get(slo.model) or {}) \
                .get("p95_ms")
        return FleetSignals(
            queue_per_worker=(sum(depths) / len(depths)) if depths else None,
            p95_ms=p95, workers_polled=len(depths),
            prefix_hit_rate=(sum(hit_rates) / len(hit_rates))
            if hit_rates else None)

    def _backfill_endpoints(self, model: str,
                            live: list[WorkerHandle]) -> None:
        """Subprocess workers register themselves; fill host/port onto the
        handles from the registry table (matched by real pid)."""
        if self.worker_registry is None:
            return
        by_pid = {w.get("pid"): w for w in self.worker_registry.workers()
                  if w.get("model") == model}
        for h in live:
            if h.host is None and h.pid in by_pid:
                w = by_pid[h.pid]
                h.host, h.port = w.get("host"), w.get("port")
                h.state = "ready"

    # -- the reconcile pass ------------------------------------------------
    def reconcile_once(self) -> list[dict]:
        t0 = time.perf_counter()
        events: list[dict] = []
        with obs.get_tracer().span("fleet.reconcile"):
            with self._lock:
                now = self.clock()
                dt = (0.0 if self._last_reconcile_at is None
                      else max(now - self._last_reconcile_at, 0.0))
                self._last_reconcile_at = now
                self._reap_draining(events)
                per_model = [(slo, self._reap_and_bill(slo, dt, events))
                             for slo in self.spec.models]
            # signal polls happen OUTSIDE the lock: N wedged /admin/stats
            # endpoints can stall for N x stats_timeout_s — exactly during
            # the overload being measured — and introspection (actual/
            # desired/live_handles) and stop() must not block on them
            polled = [(slo, live, self._signals_fn(slo, live))
                      for slo, live in per_model]
            with self._lock:
                for slo, live, sig in polled:
                    self._apply_policy(slo, sig, now, events)
            self.events.extend(events)
            del self.events[:-1000]
        _FLEET_METRICS.get()["reconcile_ms"].observe(
            (time.perf_counter() - t0) * 1e3)
        return events

    def _reap_draining(self, events: list[dict]) -> None:
        for h in list(self._draining):
            if not self.launcher.alive(h):
                self._forget(h)
                self._draining.remove(h)
                events.append(self._event(h.model, "drained"))
            elif h.drain_at is not None and \
                    self.clock() - h.drain_at > self.drain_timeout_s:
                self.launcher.kill(h)  # a wedged drain must still converge

    def _forget(self, h: WorkerHandle) -> None:
        if self.worker_registry is not None and h.pid is not None:
            self.worker_registry.remove_pid(h.pid)
        self.launcher.reap(h)

    def _event(self, model: str, direction: str, **extra) -> dict:
        live = [h for h in self._handles.get(model, ())
                if self.launcher.alive(h)]
        ev = {"t": self.clock(), "model": model, "event": direction,
              "desired": self._state[model].desired
              if model in self._state else None,
              "actual": len(live), **extra}
        _FLEET_METRICS.get()["scale_events"].inc(model=model,
                                                 direction=direction)
        return ev

    def _reap_and_bill(self, slo: ModelSLO, dt: float,
                       events: list[dict]) -> list[WorkerHandle]:
        """Phase 1 (lock held): reap crashed workers — they free their
        slots NOW so the convergence step replaces them in this same pass
        — and integrate the cost. Returns the live handles to poll."""
        handles = self._handles[slo.model]
        for h in list(handles):
            if not self.launcher.alive(h):
                handles.remove(h)
                self._forget(h)
                events.append(self._event(slo.model, "lost",
                                          handle=h.handle_id))
        live = list(handles)
        # cost integral counts DRAINING workers too — they are still
        # running (finishing their backlog) and still bill
        n_billed = len(live) + sum(1 for d in self._draining
                                   if d.model == slo.model)
        self.worker_seconds[slo.model] += dt * n_billed
        _FLEET_METRICS.get()["worker_seconds"].inc(dt * n_billed,
                                                   model=slo.model)
        return live

    def _apply_policy(self, slo: ModelSLO, sig: FleetSignals, now: float,
                      events: list[dict]) -> None:
        """Phase 2 (lock held): signals -> desired -> converge.

        Spawns/drains deliberately stay INSIDE the lock: ``stop()``
        acquires it after joining the loop thread, so an in-flight spawn
        always completes (and lands in ``_handles``) before teardown can
        enumerate what to kill — moving the actions out would reintroduce
        the leaked-worker race. The cost is that introspection can stall
        for one spawn/drain; the drain POST is bounded at 3 s and the
        expensive signal polls already run outside the lock."""
        state = self._state[slo.model]
        handles = self._handles[slo.model]
        live = [h for h in handles if self.launcher.alive(h)]
        overloaded = (
            (sig.queue_per_worker is not None
             and sig.queue_per_worker > slo.target_queue_depth)
            or (slo.p95_slo_ms is not None and sig.p95_ms is not None
                and sig.p95_ms > slo.p95_slo_ms))
        # underload needs EVIDENCE: a pass with no pollable signal (fresh
        # workers not yet registered, stats timeouts — possibly caused by
        # the very overload being measured) must not advance the
        # scale-down streak
        underloaded = (not overloaded
                       and sig.queue_per_worker is not None
                       and sig.queue_per_worker
                       <= 0.25 * slo.target_queue_depth)
        if overloaded:
            state.underload_streak = 0
            if state.desired < slo.max_workers \
                    and now - state.last_up_at >= slo.up_cooldown_s:
                state.desired = min(slo.max_workers,
                                    max(state.desired + 1,
                                        2 * max(len(live), 1)))
                state.last_up_at = now
                events.append(self._event(
                    slo.model, "up",
                    queue=sig.queue_per_worker, p95_ms=sig.p95_ms))
        elif underloaded:
            state.underload_streak += 1
            if state.underload_streak >= slo.scale_down_after \
                    and state.desired > slo.min_workers \
                    and now - state.last_down_at >= slo.down_cooldown_s:
                state.desired -= 1
                state.last_down_at = now
                state.underload_streak = 0
                events.append(self._event(slo.model, "down",
                                          queue=sig.queue_per_worker))
        else:
            state.underload_streak = 0
        state.desired = min(max(state.desired, slo.min_workers),
                            slo.max_workers)
        # 3. converge live toward desired — but never spawn once stop()
        # has been requested (a late spawn could outlive the teardown
        # snapshot; launcher.close() in stop() is the last-resort net)
        if self._stop.is_set():
            return
        while len(handles) < state.desired:
            try:
                handle = self.launcher.spawn(slo)
            except Exception as e:  # noqa: BLE001 — a failed spawn must not
                events.append(self._event(        # kill the control loop
                    slo.model, "spawn_failed", error=f"{type(e).__name__}"))
                break
            handles.append(handle)
            events.append(self._event(slo.model, "spawn",
                                      handle=handle.handle_id))
        while len(handles) > state.desired:
            victim = max(handles, key=lambda h: h.spawned_at)  # newest first
            handles.remove(victim)
            victim.state = "draining"
            victim.drain_at = self.clock()
            self._backfill_endpoints(slo.model, [victim])
            if self.launcher.drain(victim,
                                   timeout_s=self.drain_timeout_s):
                self._draining.append(victim)
                events.append(self._event(slo.model, "drain",
                                          handle=victim.handle_id))
            else:  # unreachable: treat as crashed
                self.launcher.kill(victim)
                self._forget(victim)
                events.append(self._event(slo.model, "drain_kill",
                                          handle=victim.handle_id))
        m = _FLEET_METRICS.get()
        m["desired"].set(state.desired, model=slo.model)
        m["actual"].set(len(handles), model=slo.model)
