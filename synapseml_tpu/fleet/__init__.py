"""Fleet control plane: elastic autoscaling, multi-model residency, and
admission control for the serving tier.

The serving planes (PRs 1-9) gave every worker hot swaps, AOT
zero-cold-start loads, circuit breakers, canary splits, and full
observability — but the fleet itself was still a hand-sized static worker
set. This subsystem is the control loop over those planes, driven by one
declarative :class:`~synapseml_tpu.fleet.spec.FleetSpec`:

* :mod:`.autoscaler` — :class:`FleetAutoscaler` reconciles live workers
  against per-model SLO targets (queue depth, routed p95) through a
  pluggable :class:`WorkerLauncher`; scale-up workers ``/admin/load`` their
  registry ref with AOT executables (spawn cost is I/O, not compile),
  scale-down workers drain gracefully (``POST /admin/drain``: finish the
  backlog, deregister, exit), and crashed workers are replaced within one
  reconcile interval.
* :mod:`.residency` — :class:`ResidencyManager` packs N registry models
  onto one worker behind per-model ``PipelineHolder`` slots with a
  byte-budgeted LRU; eviction rides ``release_executables`` + page-pool
  teardown, and :func:`serve_multi_model` routes request rows by the
  ``/m/<model>`` path segment.
* :mod:`.admission` — :class:`AdmissionController` puts per-model token
  buckets, priority classes (interactive > bulk), and newest-first
  p99-budget load shedding (429 + ``Retry-After``) on the routing front:
  the resilience plane's breakers protect workers, this protects SLOs.

Everything exports as ``synapseml_fleet_*`` series plus a
``fleet.reconcile`` span. See ``docs/FLEET.md``.
"""

from .spec import AdmissionPolicy, FleetSpec, ModelSLO
from .admission import (AdmissionController, AdmissionDecision, TokenBucket,
                        priority_of)
from .residency import (ResidencyManager, artifact_nbytes, model_from_path,
                        model_path, serve_multi_model)
from .autoscaler import (FleetAutoscaler, FleetSignals,
                         SubprocessWorkerLauncher, ThreadWorkerLauncher,
                         WorkerHandle, WorkerLauncher, fleet_worker_main)

__all__ = [
    "FleetSpec",
    "ModelSLO",
    "AdmissionPolicy",
    "AdmissionController",
    "AdmissionDecision",
    "TokenBucket",
    "priority_of",
    "ResidencyManager",
    "serve_multi_model",
    "model_path",
    "model_from_path",
    "artifact_nbytes",
    "FleetAutoscaler",
    "FleetSignals",
    "WorkerLauncher",
    "WorkerHandle",
    "ThreadWorkerLauncher",
    "SubprocessWorkerLauncher",
    "fleet_worker_main",
]
