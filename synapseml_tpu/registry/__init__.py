"""Model registry + deployment plane.

Content-addressed artifact store (``store``), versioned publish/resolve
with mutable aliases (``registry``), and hot-swap rollout with canary
splits, shadow traffic, and auto-rollback (``deploy``). See
``docs/REGISTRY.md`` for the publish → canary → promote → rollback
walkthrough.
"""

from .store import (ArtifactStore, IntegrityError, atomic_write_bytes,
                    sha256_file, write_stream_verified)
from .registry import (ModelRegistry, PublishedVersion, RegistryReadOnlyError,
                       ResolvedModel, param_schema_hash)
from .deploy import CanaryController, Deployment, admin_load
from .aot import (AOTCapture, AOTExecutableSet, aot_mechanism,
                  runtime_fingerprint)
from .autotune import apply_autotune, autotune_stage

__all__ = [
    "ArtifactStore",
    "IntegrityError",
    "ModelRegistry",
    "PublishedVersion",
    "ResolvedModel",
    "RegistryReadOnlyError",
    "Deployment",
    "CanaryController",
    "admin_load",
    "param_schema_hash",
    "sha256_file",
    "atomic_write_bytes",
    "write_stream_verified",
    "AOTCapture",
    "AOTExecutableSet",
    "aot_mechanism",
    "runtime_fingerprint",
    "autotune_stage",
    "apply_autotune",
]
