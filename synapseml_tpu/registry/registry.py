"""Model registry: versioned publish / resolve / pin over the artifact store.

``publish()`` turns any fitted :class:`~synapseml_tpu.core.pipeline.
PipelineStage` into a self-describing artifact: the stage tree is saved via
``core/serialization.save_stage``, every file becomes a content-addressed
blob, and a signed manifest records the stage list, a param-schema hash
(computed FROM the saved artifact, so a params refactor that changes the
wire format changes the hash), framework versions, and a metrics snapshot at
publish time. ``resolve()`` is the inverse — materialize, verify, and
``load_stage`` — and accepts either a concrete version (``v3``) or a mutable
alias (``prod``, ``canary``, ``latest``) stored as an atomically-swapped
pointer file.

The same registry layout reads back over the ``ModelDownloader`` remote
protocol: any static file server rooted at the store directory (the
in-process mock used by ``tests/test_registry.py``, or the model repository
server from ``models/downloader.py``) serves manifests, blobs, and alias
pointers as plain files. Remote registries are read-only — ``publish`` and
``pin`` are local-filesystem operations.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import platform
import tempfile
import time
import urllib.error
import urllib.request

from ..core import serialization
from .store import (ArtifactStore, IntegrityError, _canonical_json,
                    _safe_component, _version_sort_key, write_stream_verified)

__all__ = ["ModelRegistry", "ResolvedModel", "PublishedVersion",
           "RegistryReadOnlyError", "param_schema_hash"]


class RegistryReadOnlyError(RuntimeError):
    """A write operation (publish/pin) was attempted on a remote registry."""


@dataclasses.dataclass(frozen=True)
class PublishedVersion:
    name: str
    version: str
    manifest: dict
    manifest_path: str


@dataclasses.dataclass(frozen=True)
class ResolvedModel:
    stage: object
    name: str
    version: str
    manifest: dict
    path: str  # materialized stage directory


def param_schema_hash(stage_dir: str) -> str:
    """sha256 over the artifact's param schema: every ``metadata.json`` in
    the saved tree contributes (class, sorted simple-param names, sorted
    complex-param names + kinds). Two artifacts with the same hash are
    loadable by the same code; a serialization-format change flips it —
    the drift guard ``tests/test_serialization_roundtrip.py`` asserts the
    hash is stable across a save→load→save round trip."""
    entries = []
    for dirpath, _dirs, files in os.walk(stage_dir):
        if "metadata.json" not in files:
            continue
        with open(os.path.join(dirpath, "metadata.json")) as f:
            meta = json.load(f)
        rel = os.path.relpath(dirpath, stage_dir).replace(os.sep, "/")
        entries.append({
            "at": "" if rel == "." else rel,
            "class": meta.get("class", ""),
            "params": sorted(meta.get("params", {})),
            "complex": sorted((name, entry.get("kind", ""))
                              for name, entry in
                              meta.get("complexParams", {}).items()),
        })
    entries.sort(key=lambda e: e["at"])
    return hashlib.sha256(_canonical_json(entries)).hexdigest()


def _framework_versions() -> dict:
    import numpy

    versions = {"python": platform.python_version(),
                "numpy": numpy.__version__}
    try:  # jax may be absent/broken in minimal consumers; record if present
        import jax

        versions["jax"] = jax.__version__
    except Exception:  # noqa: BLE001 - any import failure just omits the key
        pass
    return versions


class ModelRegistry:
    """Publish/resolve/pin pipeline versions against a local store directory
    or a read-only remote (``http(s)://``) registry.

    ``cache_dir`` is where resolved versions materialize (default:
    ``<root>/.cache`` locally, a per-user dir for remotes). A version is
    materialized once — the ``.complete`` marker makes re-resolution a pure
    ``load_stage``.
    """

    def __init__(self, root: str, cache_dir: str | None = None,
                 timeout_s: float = 10.0):
        self.root = root.rstrip("/") if root.startswith(("http://", "https://")) \
            else os.path.abspath(root)
        self.is_remote = self.root.startswith(("http://", "https://"))
        self.timeout_s = timeout_s
        self._store = None if self.is_remote else ArtifactStore(self.root)
        if cache_dir is None:
            if self.is_remote:
                digest = hashlib.sha256(self.root.encode()).hexdigest()[:16]
                cache_dir = os.path.join(
                    tempfile.gettempdir(),
                    f"synapseml_registry_cache_{digest}")
            else:
                cache_dir = os.path.join(self.root, ".cache")
        self.cache_dir = cache_dir

    # -- remote plumbing (ModelDownloader protocol: plain files over HTTP) --
    def _open_remote(self, rel: str):
        url = f"{self.root}/{rel}"
        try:
            return urllib.request.urlopen(url, timeout=self.timeout_s)
        except urllib.error.HTTPError as e:
            raise RuntimeError(f"registry server returned {e.code} for "
                               f"{url!r}: {e.reason}") from e
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise RuntimeError(
                f"registry unreachable at {url!r}: {e}. On zero-egress "
                "hosts point ModelRegistry at a local store directory "
                "instead.") from e

    def _read_remote(self, rel: str) -> bytes:
        with self._open_remote(rel) as r:
            return r.read()

    def _require_local(self, op: str) -> ArtifactStore:
        if self._store is None:
            raise RegistryReadOnlyError(
                f"{op}() needs a local registry; {self.root!r} is remote "
                "(read-only)")
        return self._store

    # -- listing / refs ----------------------------------------------------
    def list_versions(self, name: str) -> list[str]:
        if self._store is not None:
            return self._store.list_versions(name)
        try:
            index = json.loads(self._read_remote(
                f"manifests/{_safe_component(name)}/index.json"))
        except RuntimeError:
            return []
        return sorted((str(v) for v in index), key=_version_sort_key)

    def aliases(self, name: str) -> dict[str, str]:
        if self._store is not None:
            return self._store.list_aliases(name)
        # the remote protocol has no directory listing for aliases; probe
        # the conventional set (the deployment plane only moves these)
        out = {}
        for alias in ("latest", "prod", "canary"):
            target = self.alias_target(name, alias)
            if target:
                out[alias] = target
        return out

    def alias_target(self, name: str, alias: str) -> str | None:
        if self._store is not None:
            return self._store.read_alias(name, alias)
        try:
            return self._read_remote(
                f"aliases/{_safe_component(name)}/"
                f"{_safe_component(alias)}").decode().strip() or None
        except RuntimeError:
            return None

    def resolve_ref(self, name: str, ref: str) -> str:
        """A concrete version for ``ref`` (version string or alias)."""
        versions = self.list_versions(name)
        if ref in versions:
            return ref
        target = self.alias_target(name, ref)
        if target is not None:
            if target not in versions:
                raise KeyError(
                    f"alias {name}:{ref} points at missing version "
                    f"{target!r}")
            return target
        raise KeyError(f"{name}:{ref} is neither a version nor an alias "
                       f"(versions: {versions or 'none'})")

    def next_version(self, name: str) -> str:
        nums = [int(v[1:]) for v in self.list_versions(name)
                if v.startswith("v") and v[1:].isdigit()]
        return f"v{max(nums, default=0) + 1}"

    def manifest(self, name: str, ref: str = "latest") -> dict:
        return self._manifest_for_version(name, self.resolve_ref(name, ref))

    def _manifest_for_version(self, name: str, version: str) -> dict:
        """Manifest for an already-concrete version (no re-resolution — a
        remote resolve must not pay a second index.json round trip)."""
        if self._store is not None:
            return self._store.read_manifest(name, version)
        return json.loads(self._read_remote(
            f"manifests/{_safe_component(name)}/"
            f"{_safe_component(version)}.json"))

    # -- publish -----------------------------------------------------------
    def publish(self, name: str, stage, version: str | None = None,
                metrics: dict | None = None, extra: dict | None = None,
                set_latest: bool = True, aot: dict | None = None,
                autotune: dict | None = None,
                sharding=None, extra_tree: str | None = None) -> PublishedVersion:
        """Save ``stage``, blobify its tree, and write the signed manifest.
        ``version`` defaults to the next ``v<N>``; ``metrics`` is the
        caller's evaluation snapshot at publish time (what the deployment
        plane compares a canary against). ``extra_tree`` names a directory
        whose contents are merged into the artifact tree before blobify —
        sidecar data (e.g. retrieval index shards) that must version, GC
        and materialize with the stage.

        ``aot`` turns on publish-time AOT compilation of the serve ladder
        (the TVM pay-compile-once discipline — ``registry/aot.py``):
        ``{"rows": [<sample request bodies>], "buckets": [...],
        "input_col": ..., "parse_json": ...}``. A fresh reload of the
        saved artifact is driven through the serve-loop warmup at every
        bucket, each compiled executable is serialized and stored
        content-addressed next to the weights, and the manifest records
        the entries + the runtime fingerprint (platform, jax/jaxlib,
        XLA-flags sha) that gates their reuse. ``buckets`` defaults to the
        process-wide ladder. ``autotune`` (same ``rows``-driven harness,
        plus optional ``{"winners": {...}}`` overrides from the decision
        benches) searches any stage-declared ``_AUTOTUNE_PARAMS`` backend
        candidates and pins the fastest per platform into the manifest —
        the AOT capture then compiles the winning kernels.

        ``sharding`` records the declarative sharding plane in the
        manifest: a ``parallel.partition.PartitionRules`` (its ``mesh``
        field names the target topology), a prebuilt section dict, or
        ``"auto"`` to lift the stage's own ``partition_rules``/
        ``mesh_config`` params. ``/admin/load`` re-applies the section
        BEFORE warmup — a loading host whose devices cannot build the
        recorded mesh demotes to a replicated load with one structured
        warning instead of a failed swap."""
        store = self._require_local("publish")
        _safe_component(name)
        version = _safe_component(version or self.next_version(name))
        if version in self.list_versions(name):
            raise FileExistsError(
                f"{name}:{version} already published (versions are "
                "immutable; pick a new version or alias)")
        aot_section = tune_section = None
        with tempfile.TemporaryDirectory(prefix="synapseml_publish_") as tmp:
            stage_dir = os.path.join(tmp, "stage")
            serialization.save_stage(stage, stage_dir)
            if extra_tree is not None:
                # sidecar data riding the artifact (retrieval index shards):
                # merged into the stage tree BEFORE ingest, so the files are
                # content-addressed blobs on the manifest ``files`` list —
                # deduped across versions, GC-protected, materialized under
                # ``resolve().path`` like any other artifact byte
                import shutil

                shutil.copytree(extra_tree, stage_dir, dirs_exist_ok=True)
            files = store.ingest_tree(stage_dir)
            stages = _stage_classes(stage_dir)
            schema_hash = param_schema_hash(stage_dir)
            if aot is not None or autotune is not None:
                aot_section, tune_section = self._publish_compile(
                    stage_dir, store, aot, autotune)
        manifest = {
            "name": name,
            "version": version,
            "created_at_unix": time.time(),
            "stages": stages,
            "param_schema_sha256": schema_hash,
            "framework": _framework_versions(),
            "metrics": dict(metrics or {}),
            "files": files,
            "total_bytes": sum(e["bytes"] for e in files),
        }
        if aot_section is not None:
            manifest["aot"] = aot_section
        if tune_section is not None:
            manifest["autotune"] = tune_section
        shard_section = self._sharding_section(stage, sharding)
        if shard_section is not None:
            manifest["sharding"] = shard_section
        if extra:
            manifest["extra"] = dict(extra)
        path = store.write_manifest(name, version, manifest)
        if set_latest:
            store.write_alias(name, "latest", version)
        return PublishedVersion(name, version, manifest, path)

    def _sharding_section(self, stage, sharding) -> dict | None:
        """Build the manifest's ``sharding`` section. Accepts a
        ``PartitionRules``, a prebuilt section dict (``{"rules": ...}``),
        or ``"auto"`` (lift the stage's own ``partition_rules`` +
        ``mesh_config`` params — the publish path for a stage already
        configured for sharded serving). Per-leaf spec digests are added
        when the stage exposes a ``model_params`` pytree."""
        if sharding is None:
            return None
        from ..parallel import partition as pp
        from ..parallel.mesh import MeshConfig

        if isinstance(sharding, dict) and "rules" in sharding:
            return dict(sharding)
        target = pp.sharding_target(stage)
        if sharding == "auto":
            if target is None:
                raise ValueError(
                    f"publish(sharding='auto'): stage "
                    f"{type(stage).__name__} has no partition_rules/"
                    "mesh_config params to lift (nested stages searched)")
            mesh_cfg = target.get("mesh_config")
            rules = target.get("partition_rules") \
                or pp.default_llama_rules(mesh=mesh_cfg)
            if mesh_cfg is None:
                raise ValueError(
                    "publish(sharding='auto'): stage has no mesh_config "
                    "set — there is no topology to record")
            if rules.mesh is None:
                import dataclasses as dc

                rules = dc.replace(rules, mesh=mesh_cfg)
            sharding = rules
        if not isinstance(sharding, pp.PartitionRules):
            raise TypeError(
                f"sharding must be a PartitionRules, a section dict or "
                f"'auto', got {type(sharding).__name__}")
        if sharding.mesh is None:
            raise ValueError(
                "publish(sharding=...): the rule table must carry its "
                "target mesh (PartitionRules(mesh=MeshConfig(...))) so "
                "/admin/load can rebuild the topology")
        assert isinstance(sharding.mesh, MeshConfig)
        params = None
        if target is not None and callable(getattr(target, "has_param",
                                                   None)) \
                and target.has_param("model_params"):
            params = target.get("model_params")
        return pp.sharding_manifest_section(sharding, params)

    def _publish_compile(self, stage_dir: str, store: ArtifactStore,
                         aot: dict | None, autotune: dict | None):
        """The offline compile pass: reload the JUST-SAVED artifact (fresh
        instances — exactly what a worker will load, with no warm cache
        entries hiding rungs from capture), autotune backends first (the
        capture must compile the winners), then AOT the ladder."""
        from ..core import batching as cb
        from . import aot as raot

        spec = dict(aot or {})
        rows = spec.get("rows") or (autotune or {}).get("rows")
        if not rows and aot is not None:
            raise ValueError(
                "publish(aot=...) needs sample request rows to drive the "
                "ladder: aot={'rows': [<request bodies>], ...}")
        loop_cfg = {"parse_json": spec.get("parse_json", True),
                    "input_col": spec.get("input_col", "body")}
        buckets = spec.get("buckets")
        if buckets is None:
            buckets = cb.default_bucketer().buckets_upto(
                int(spec.get("max_rows", cb.default_bucketer().max_bucket)))
        loaded = serialization.load_stage(stage_dir)
        tune_section = None
        if autotune is not None:
            from .autotune import autotune_stage

            tune_section = autotune_stage(
                loaded, rows or [], buckets, loop_cfg,
                trials=int(autotune.get("trials", 2)),
                winners=autotune.get("winners"))
            # the search drove every stage through the process cache —
            # evict the tree's executables so the capture below sees
            # FRESH misses (warm entries would hide whole rungs from the
            # AOT artifact)
            cb.release_executables(loaded)
        aot_section = None
        if aot is not None:
            aot_section = raot.capture_stage_ladder(
                loaded, rows, buckets, loop_cfg, store.put_blob_bytes)
        return aot_section, tune_section

    # -- resolve -----------------------------------------------------------
    def resolve(self, name: str, ref: str = "latest") -> ResolvedModel:
        """Materialize + integrity-verify + ``load_stage`` one version."""
        version = self.resolve_ref(name, ref)
        manifest = self._manifest_for_version(name, version)
        dest = os.path.join(self.cache_dir, _safe_component(name),
                            _safe_component(version))
        marker = os.path.join(dest, ".complete")
        if not os.path.isfile(marker):
            # serialize materialization per version: two workers resolving
            # the same version concurrently (a fleet-wide hot swap) must not
            # race the build-then-rename
            import fcntl

            os.makedirs(dest, exist_ok=True)
            with open(os.path.join(dest, ".lock"), "w") as lockf:
                fcntl.flock(lockf, fcntl.LOCK_EX)
                if not os.path.isfile(marker):
                    self._materialize(name, version, manifest, dest)
                    with open(marker, "w") as f:
                        f.write(version)
        else:
            # marker present: the stage tree is complete, but AOT blobs
            # that failed a transient fetch self-heal here (cheap isfile
            # scan when everything is already on disk)
            self._ensure_aot_blobs(manifest, dest)
        stage = serialization.load_stage(os.path.join(dest, "stage"))
        return ResolvedModel(stage=stage, name=name, version=version,
                             manifest=manifest,
                             path=os.path.join(dest, "stage"))

    def _materialize(self, name: str, version: str, manifest: dict,
                     dest: str) -> None:
        cache_store = ArtifactStore(self.cache_dir) if self.is_remote \
            else self._store

        def fetch(digest: str, path: str) -> None:
            # remote blobs mirror into the cache's blob dir first, so a
            # version re-resolve and shared blobs across versions hit the
            # network once
            if not cache_store.has_blob(digest):
                blob = cache_store.blob_path(digest)
                os.makedirs(os.path.dirname(blob), exist_ok=True)
                with self._open_remote(f"blobs/{digest}") as r:
                    write_stream_verified(r, blob, digest)
            cache_store.materialize_blob(digest, path)

        stage_root = os.path.join(dest, "stage")
        cache_store.materialize_tree(
            manifest["files"], stage_root,
            fetch=fetch if self.is_remote else None)
        self._ensure_aot_blobs(manifest, dest)
        got = param_schema_hash(stage_root)
        want = manifest.get("param_schema_sha256")
        if want and got != want:
            raise IntegrityError(
                f"{name}:{version} param schema hash mismatch: manifest "
                f"{want}, materialized {got} — artifact and manifest "
                "disagree")

    def _ensure_aot_blobs(self, manifest: dict, dest: str) -> None:
        """Materialize the manifest's AOT executable blobs into
        ``dest/aot/<sha256>``. Idempotent and SELF-HEALING: called on every
        resolve (cheap ``isfile`` checks once present), so a transient
        fetch failure is retried next resolve instead of becoming a
        permanent per-worker JIT fallback behind the ``.complete`` marker.
        A still-missing blob is skipped, never fatal — the load path
        demotes that entry to tracing."""
        entries = (manifest.get("aot") or {}).get("entries", ())
        if not entries:
            return
        cache_store = ArtifactStore(self.cache_dir) if self.is_remote \
            else self._store
        for entry in entries:
            digest = entry.get("sha256")
            if not digest:
                continue
            blob_dest = os.path.join(dest, "aot", digest)
            if os.path.isfile(blob_dest):
                continue
            try:
                if self.is_remote:
                    blob = cache_store.blob_path(digest)
                    if not cache_store.has_blob(digest):
                        os.makedirs(os.path.dirname(blob), exist_ok=True)
                        with self._open_remote(f"blobs/{digest}") as r:
                            write_stream_verified(r, blob, digest)
                    cache_store.materialize_blob(digest, blob_dest)
                else:
                    cache_store.materialize_blob(digest, blob_dest)
            except (OSError, RuntimeError, IntegrityError):
                continue

    # -- pin (atomic alias swap) -------------------------------------------
    def pin(self, name: str, alias: str, ref: str) -> str:
        """Point ``alias`` at a version (atomic pointer-file swap); returns
        the concrete version pinned. ``ref`` may itself be an alias."""
        store = self._require_local("pin")
        version = self.resolve_ref(name, ref)
        store.write_alias(name, alias, version)
        return version


def _stage_classes(stage_dir: str) -> list[str]:
    """The artifact's stage class list: the root metadata class plus any
    nested stage/stage_list complex params, in tree order."""
    out = []

    def walk(d: str) -> None:
        meta_path = os.path.join(d, "metadata.json")
        if not os.path.isfile(meta_path):
            return
        with open(meta_path) as f:
            meta = json.load(f)
        out.append(meta.get("class", ""))
        for name, entry in sorted(meta.get("complexParams", {}).items()):
            target = os.path.join(d, f"complex_{name}")
            if entry.get("kind") == "stage":
                walk(target)
            elif entry.get("kind") == "stage_list":
                for i in range(int(entry.get("n", 0))):
                    walk(f"{target}_{i:03d}")

    walk(stage_dir)
    return out
