"""Deployment plane: hot-swap rollout, canary splits, auto-rollback.

Ties the registry (versioned artifacts, mutable aliases) to the serving
fleet (``io/serving.py`` workers with ``POST /admin/load``; the
``io/distributed_serving.RoutingFront`` with weighted splits and shadow
traffic). The flow a rollout follows::

    publish v2 ──> Deployment.canary("v2", weight=0.1)
                     │  POST /admin/load on N workers (side-by-side load,
                     │  warmup batch, atomic swap, re-register)
                     │  front.set_traffic_split({stable: 0.9, v2: 0.1})
                     ▼
                CanaryController (polls front.version_stats())
                     │  errors feed a core.resilience.CircuitBreaker;
                     │  p95 regression vs the stable version checked too
          breaker OPEN│                                 │healthy long enough
                     ▼                                 ▼
                rollback: split→stable, alias back,    promote(): load on
                reload stable on swapped workers       all workers, pin prod

The controller deliberately reuses :class:`~synapseml_tpu.core.resilience.
CircuitBreaker` for the trip decision — the canary is "a worker pool behind
a breaker": a failure-rate window with a minimum sample count, so one
unlucky request cannot roll back a healthy canary, and a genuinely broken
version trips within ``window`` requests.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

from ..core import observability as obs
from ..core.resilience import CircuitBreaker

__all__ = ["Deployment", "CanaryController", "admin_load"]

_DEPLOY_METRICS = obs.HandleCache(lambda reg: {
    "events": reg.counter(
        "synapseml_deploy_events_total",
        "deployment plane events (swap/canary/promote/rollback)",
        ("event",)),
})


def _post_json(url: str, payload: dict, timeout_s: float) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        body = e.read()
        try:
            detail = json.loads(body).get("error", body.decode(errors="replace"))
        except (json.JSONDecodeError, UnicodeDecodeError):
            detail = body.decode(errors="replace")
        raise RuntimeError(f"{url} returned {e.code}: {detail}") from e


def admin_load(endpoint: str, registry_root: str, model: str, ref: str,
               warmup: list | None = None, version: str | None = None,
               timeout_s: float = 120.0, warmup_buckets: list | None = None,
               use_aot: bool = True, use_autotune: bool = True,
               use_sharding: bool = True) -> dict:
    """Hot-swap one worker (``endpoint`` = ``http://host:port``) to a
    registry version via its ``POST /admin/load``. Returns the worker's
    reply (``{"ok": true, "version": ..., "previous": ..., "warmup":
    {<breakdown>}}``); raises with the worker's error detail when the load
    or warmup failed (the worker keeps serving its old pipeline in that
    case). ``use_aot=False`` / ``use_autotune=False`` force the JIT-warmup
    / saved-defaults path even when the artifact ships AOT executables or
    autotuned backend pins (the coldstart bench's A/B switches).
    ``use_sharding=False`` forces a replicated load even when the
    manifest carries a ``sharding`` section (the worker otherwise
    re-applies the rule table + mesh before warmup)."""
    payload: dict = {"registry": registry_root, "model": model, "ref": ref}
    if warmup:
        payload["warmup"] = list(warmup)
    if warmup_buckets:
        payload["warmup_buckets"] = [int(b) for b in warmup_buckets]
    if version:
        payload["version"] = version
    if not use_aot:
        payload["aot"] = False
    if not use_autotune:
        payload["autotune"] = False
    if not use_sharding:
        payload["sharding"] = False
    return _post_json(endpoint.rstrip("/") + "/admin/load", payload,
                      timeout_s)


class Deployment:
    """Rollout orchestration for one model on one serving fleet.

    ``serving`` is a ``DistributedServing`` handle (or any object with a
    ``front`` and a ``registry`` whose ``workers()`` lists registrations);
    ``registry`` is the :class:`~synapseml_tpu.registry.ModelRegistry` the
    versions were published to. All state transitions emit
    ``synapseml_deploy_events_total`` and move aliases atomically."""

    def __init__(self, serving, registry, model: str,
                 warmup: list | None = None, alias: str = "prod",
                 timeout_s: float = 120.0, use_aot: bool = True,
                 use_sharding: bool = True):
        self.serving = serving
        self.registry = registry
        self.model = model
        self.alias = alias
        self.warmup = list(warmup or [])
        self.timeout_s = timeout_s
        self.use_aot = use_aot
        self.use_sharding = use_sharding
        # per-rollout aggregate of the workers' warmup breakdowns — the
        # operator's one-glance answer to "did this rollout ride AOT?"
        self.last_rollout: dict | None = None
        self._controller: CanaryController | None = None

    # -- fleet introspection ----------------------------------------------
    def workers(self) -> list[dict]:
        return self.serving.registry.workers()

    def workers_by_version(self) -> dict[str, list[dict]]:
        from ..io.distributed_serving import _version_of

        out: dict[str, list[dict]] = {}
        for w in self.workers():
            out.setdefault(_version_of(w), []).append(w)
        return out

    def stable_version(self) -> str:
        """The version serving the majority of the fleet (ties: the alias
        target, then the lexicographically first)."""
        by_version = self.workers_by_version()
        if not by_version:
            raise RuntimeError("no workers registered")
        pinned = self.registry.alias_target(self.model, self.alias)
        return sorted(by_version,
                      key=lambda v: (-len(by_version[v]), v != pinned, v))[0]

    def _endpoint(self, w: dict) -> str:
        return f"http://{w.get('host')}:{w.get('port')}"

    def _load_on(self, targets: list[dict], ref: str) -> list[dict]:
        replies = []
        for w in targets:
            replies.append(admin_load(
                self._endpoint(w), self.registry.root, self.model, ref,
                warmup=self.warmup, timeout_s=self.timeout_s,
                use_aot=self.use_aot, use_sharding=self.use_sharding))
        self.last_rollout = self._rollout_summary(ref, replies)
        return replies

    @staticmethod
    def _rollout_summary(ref: str, replies: list[dict]) -> dict:
        """Aggregate the workers' /admin/load warmup breakdowns: total
        swap wall, AOT hit/trace counts, and which workers fell back to
        JIT (a mixed fleet is the signal an operator needs to see)."""
        summary = {"ref": ref, "workers": len(replies),
                   "total_load_ms": 0.0, "io_ms": 0.0, "compile_ms": 0.0,
                   "aot_hits": 0, "executables_traced": 0,
                   "modes": {}, "fallback_reasons": []}
        for reply in replies:
            summary["total_load_ms"] += float(reply.get("load_ms", 0.0))
            wu = reply.get("warmup") or {}
            summary["io_ms"] += float(wu.get("io_ms", 0.0))
            summary["compile_ms"] += float(wu.get("compile_ms", 0.0))
            summary["aot_hits"] += int(wu.get("aot_hits", 0))
            summary["executables_traced"] += int(
                wu.get("executables_traced", 0))
            mode = wu.get("mode", "jit")
            summary["modes"][mode] = summary["modes"].get(mode, 0) + 1
            if wu.get("fallback_reason"):
                summary["fallback_reasons"].append(wu["fallback_reason"])
        for field in ("total_load_ms", "io_ms", "compile_ms"):
            summary[field] = round(summary[field], 2)
        return summary

    def _wait_registered(self, version: str, n: int,
                         timeout_s: float = 10.0) -> None:
        """Swapped workers re-register asynchronously; the split must not
        activate before the front can route to the new version."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if len(self.workers_by_version().get(version, ())) >= n:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"{n} worker(s) did not re-register as {version!r} within "
            f"{timeout_s}s")

    # -- rollout verbs -----------------------------------------------------
    def canary(self, ref: str, weight: float = 0.05,
               num_workers: int = 1, shadow: bool = False,
               autorollback: dict | None = None) -> "CanaryController | None":
        """Start a canary: hot-swap ``num_workers`` workers to ``ref``, pin
        the ``canary`` alias, and split traffic ``1-weight / weight``
        between the stable version and the canary. ``shadow=True``
        additionally mirrors stable traffic to the canary (read-only
        comparison). ``autorollback`` (dict of CanaryController kwargs, or
        ``{}`` for defaults) starts the watchdog and returns it."""
        stable = self.stable_version()
        version = self.registry.resolve_ref(self.model, ref)
        if version == stable:
            raise ValueError(f"canary {version!r} is already the stable "
                             "version")
        targets = [w for w in self.workers()
                   if w.get("version") != version][:max(num_workers, 1)]
        if not targets:
            raise RuntimeError("no workers available to canary onto")
        self._load_on(targets, version)
        self._wait_registered(version, len(targets))
        self.registry.pin(self.model, "canary", version)
        front = self.serving.front
        front.set_traffic_split({stable: 1.0 - weight, version: weight})
        if shadow:
            front.set_shadow(version)
        _DEPLOY_METRICS.get()["events"].inc(event="canary")
        if autorollback is not None:
            self._controller = CanaryController(
                front, stable=stable, canary=version, deployment=self,
                **autorollback)
            self._controller.start()
            return self._controller
        return None

    def promote(self, ref: str | None = None) -> str:
        """Roll the canary (or ``ref``) to the whole fleet: load it on every
        worker, clear the split/shadow, pin the ``prod`` alias."""
        version = self.registry.resolve_ref(
            self.model, ref if ref is not None else "canary")
        self.stop_controller()
        targets = [w for w in self.workers() if w.get("version") != version]
        if targets:
            self._load_on(targets, version)
            self._wait_registered(version, len(self.workers()))
        front = self.serving.front
        front.set_traffic_split(None)
        front.clear_shadow()
        self.registry.pin(self.model, self.alias, version)
        _DEPLOY_METRICS.get()["events"].inc(event="promote")
        return version

    def rollback(self, stable: str | None = None,
                 reload_workers: bool = True) -> str:
        """Flip everything back to the stable version: route 100% of
        traffic to it, pin the alias back, and (by default) reload it on
        the workers that had been swapped to the canary."""
        stable = stable or self.stable_version()
        front = self.serving.front
        front.set_traffic_split({stable: 1.0})
        front.clear_shadow()
        self.registry.pin(self.model, self.alias, stable)
        if reload_workers:
            strays = [w for w in self.workers()
                      if w.get("version") not in (stable, None)]
            for w in strays:
                try:
                    admin_load(self._endpoint(w), self.registry.root,
                               self.model, stable, warmup=self.warmup,
                               timeout_s=self.timeout_s,
                               use_aot=self.use_aot,
                               use_sharding=self.use_sharding)
                except (RuntimeError, OSError):
                    # an unreachable canary worker stays excluded by the
                    # split; the supervisor/breaker planes own its health
                    pass
        _DEPLOY_METRICS.get()["events"].inc(event="rollback")
        return stable

    def stop_controller(self) -> None:
        if self._controller is not None:
            self._controller.stop()
            self._controller = None


class CanaryController:
    """Auto-rollback watchdog for an active canary.

    Polls ``front.version_stats()`` every ``interval_s`` and feeds each new
    canary outcome into a :class:`CircuitBreaker` configured with a
    failure-rate window (``error_rate_threshold`` over the last ``window``
    outcomes, at least ``min_samples`` seen). The breaker OPENING — or the
    canary's p95 latency exceeding ``p95_regression_factor`` × the stable
    version's p95 with enough samples — triggers exactly one rollback:
    traffic snaps to the stable version, the alias flips back, and (when
    constructed by :meth:`Deployment.canary`) the swapped workers reload
    the stable version. ``rolled_back``/``reason`` record the verdict."""

    def __init__(self, front, stable: str, canary: str,
                 deployment: Deployment | None = None,
                 registry=None, model: str | None = None,
                 alias: str = "prod",
                 error_rate_threshold: float = 0.5, window: int = 20,
                 min_samples: int = 3, p95_regression_factor: float = 0.0,
                 min_latency_samples: int = 20,
                 interval_s: float = 0.25,
                 on_rollback=None):
        self.front = front
        self.stable = stable
        self.canary = canary
        self.deployment = deployment
        self.registry = registry if registry is not None else (
            deployment.registry if deployment is not None else None)
        self.model = model or (deployment.model
                               if deployment is not None else None)
        self.alias = alias
        self.p95_regression_factor = float(p95_regression_factor)
        self.min_latency_samples = int(min_latency_samples)
        self.interval_s = float(interval_s)
        self.on_rollback = on_rollback
        self.rolled_back = False
        self.reason: str | None = None
        self._breaker = CircuitBreaker(
            failure_rate_threshold=error_rate_threshold, window=window,
            min_samples=min_samples, name=f"canary {canary}")
        # baseline against the front's CUMULATIVE counters at construction:
        # a long-lived front carries history from earlier rollouts of the
        # same version, and replaying it into the fresh breaker would trip
        # a healthy re-canary before it serves a single new request
        baseline = self.front.version_stats().get(canary, {})
        self._seen = {"ok": baseline.get("ok", 0),
                      "err": baseline.get("err", 0)}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self) -> "CanaryController":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            reason = self.check_once()
            if reason is not None:
                self._trip(reason)
                return

    def check_once(self) -> str | None:
        """One poll: feed new outcomes, return a rollback reason or None.
        Public so tests (and callers without a thread) can drive it
        deterministically."""
        stats = self.front.version_stats()
        canary = stats.get(self.canary, {})
        ok, err = canary.get("ok", 0), canary.get("err", 0)
        for _ in range(max(ok - self._seen["ok"], 0)):
            self._breaker.record_success()
        for _ in range(max(err - self._seen["err"], 0)):
            self._breaker.record_failure()
        self._seen = {"ok": ok, "err": err}
        if self._breaker.state != CircuitBreaker.CLOSED:
            total = ok + err
            return (f"canary {self.canary} error rate tripped the breaker "
                    f"({err}/{total} failed)")
        if self.p95_regression_factor > 0:
            stable = stats.get(self.stable, {})
            c_p95, s_p95 = canary.get("p95_ms"), stable.get("p95_ms")
            if (c_p95 is not None and s_p95 is not None and s_p95 > 0
                    and canary.get("n_latencies", 0)
                    >= self.min_latency_samples
                    and c_p95 > self.p95_regression_factor * s_p95):
                return (f"canary {self.canary} p95 {c_p95:.1f}ms > "
                        f"{self.p95_regression_factor:g}x stable "
                        f"{s_p95:.1f}ms")
        return None

    def _trip(self, reason: str) -> None:
        self.reason = reason
        self.rolled_back = True
        if self.deployment is not None:
            self.deployment.rollback(stable=self.stable)
        else:
            self.front.set_traffic_split({self.stable: 1.0})
            self.front.clear_shadow()
            if self.registry is not None and self.model:
                self.registry.pin(self.model, self.alias, self.stable)
            _DEPLOY_METRICS.get()["events"].inc(event="rollback")
        if self.on_rollback is not None:
            try:
                self.on_rollback(reason)
            except Exception:  # noqa: BLE001 - observer must not undo the rollback
                pass
