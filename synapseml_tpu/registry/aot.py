"""AOT-compiled executable ladders: publish-time compilation, load-time reuse.

The TVM lesson (PAPERS.md, arXiv:1802.04799) applied to the deploy plane:
compile-time work belongs *offline*. Every ``/admin/load`` hot-swap used to
pay jit traces at warmup — bounded by the PR-4 bucket ladder, but still the
dominant cost of a fleet rollout, and heavy models had to cap default warmup
at small rungs to stay inside the deploy-plane load timeout. This module
moves that cost to ``registry.publish``:

* **Capture** (:class:`AOTCapture`) — during a publish-time warmup drive of
  the saved stage, every :class:`~synapseml_tpu.core.batching.CompiledCache`
  miss records its built jit and first-call arguments. ``export()`` then
  AOT-lowers each one (``jit(...).lower(...).compile()``) and serializes the
  compiled executable.
* **Mechanism feature-detection** (:func:`aot_mechanism`) — prefers the raw
  XLA executable round-trip (``client.serialize_executable`` /
  ``deserialize_executable``: a true zero-compile load), falls back to
  ``jax.export`` StableHLO blobs (skips Python tracing; XLA still compiles
  at load), and degrades to ``None`` (plain JIT warmup) when neither exists.
* **Keying** — every entry is addressed by ``(fn_id, bucket shape, dtype)``
  digest plus the *runtime fingerprint* ``(platform, jax, jaxlib, XLA-flags
  sha)``. A stale key can never load into the wrong runtime: any mismatch
  is a structured warning + JIT fallback, never a wrong executable.
* **Instance binding** — cache keys discriminate stage instances by
  process-local tokens (``core.batching.instance_token``), which cannot
  travel across processes. Entries instead record the *first-seen ordinal*
  of their instance during the publish warmup drive; at load the provider
  re-binds ordinals in first-seen order while the worker replays the SAME
  manifest-recorded warmup (rows + buckets), single-threaded. Two stages of
  one pipeline always fire in pipeline order under identical batch
  preparation, so ordinal ``k`` at load is the stage that was ordinal ``k``
  at publish. Binding is restricted to the warmup thread and frozen after
  it — a concurrent serve loop on the old pipeline can never pollute the
  ordering.
* **Load tier** (:class:`AOTExecutableSet`) — installed as a second tier on
  the ``CompiledCache``: a miss consults the artifact's executable blobs
  (sha256-verified on read) before tracing, so ``/admin/load`` maps in
  precompiled executables and the first post-swap request runs with zero
  compile stalls. Corrupt or missing blobs fall back to tracing per entry.
"""

from __future__ import annotations

import functools
import hashlib
import json
import logging
import os
import threading
import time

import numpy as np

from ..core import observability as obs
from .store import IntegrityError, _canonical_json

__all__ = [
    "AOTError", "AOTCapture", "AOTExecutableSet",
    "aot_mechanism", "runtime_fingerprint", "fingerprint_mismatch",
    "aot_key_digest", "capture_stage_ladder", "walk_stages",
    "emit_load_metrics",
]

logger = logging.getLogger("synapseml_tpu.registry.aot")

# deploy-plane warmup observability (satellite: the same fields the
# /admin/load reply breaks down, as synapseml_deploy_* series)
_AOT_METRICS = obs.HandleCache(lambda reg: {
    "io_ms": reg.histogram(
        "synapseml_deploy_warmup_io_ms",
        "per-swap wall time spent materializing + deserializing AOT "
        "executable blobs (plus registry resolve I/O)").labels(),
    "compile_ms": reg.histogram(
        "synapseml_deploy_warmup_compile_ms",
        "per-swap wall time spent tracing/compiling during warmup (zero "
        "when the full ladder rode the AOT path)").labels(),
    "aot_hits": reg.counter(
        "synapseml_deploy_aot_hits_total",
        "warmup cache misses served from AOT executable blobs").labels(),
    "aot_misses": reg.counter(
        "synapseml_deploy_aot_misses_total",
        "warmup cache misses with no matching AOT blob (traced "
        "instead)").labels(),
    "loaded": reg.counter(
        "synapseml_deploy_executables_loaded_total",
        "distinct precompiled executables deserialized at load").labels(),
    "traced": reg.counter(
        "synapseml_deploy_executables_traced_total",
        "executables traced+compiled during /admin/load warmup").labels(),
    "fallbacks": reg.counter(
        "synapseml_deploy_aot_fallbacks_total",
        "swaps that fell back to JIT warmup despite the artifact shipping "
        "AOT blobs", ("reason",)),
})


class AOTError(RuntimeError):
    """An AOT executable blob cannot serve the requested call."""


# ---------------------------------------------------------------------------
# mechanism feature-detection
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=1)
def aot_mechanism() -> str | None:
    """Best executable-serialization mechanism this jax/jaxlib supports:
    ``"xla"`` (raw ``serialize_executable`` round-trip — zero-compile
    loads), ``"export"`` (``jax.export`` StableHLO — skips tracing, XLA
    still compiles at load), or ``None`` (no AOT; plain JIT warmup). Probed
    once per process with a trivial program."""
    def _build_probe():
        import jax

        return jax.jit(lambda x: x + 1)

    try:
        import jax
        import jax.numpy as jnp

        comp = _build_probe().lower(
            jnp.zeros((2,), jnp.float32)).compile()
        rexec = comp.runtime_executable()
        blob = rexec.client.serialize_executable(rexec)
        de = rexec.client.deserialize_executable(bytes(blob), None)
        out = de.execute([jax.device_put(np.ones(2, np.float32))])
        if float(np.asarray(out[0])[0]) == 2.0:
            return "xla"
    except Exception:  # noqa: BLE001 - any probe failure just demotes
        pass
    try:
        from jax import export as jexport

        del jexport
        return "export"
    except Exception:  # noqa: BLE001
        return None


def runtime_fingerprint() -> dict:
    """The key components that make an executable blob loadable: platform,
    jax/jaxlib versions, and an XLA-flags fingerprint (device-count and
    optimization flags change compiled code and device topology)."""
    import jax
    import jaxlib

    return {
        "platform": jax.default_backend(),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "xla_flags_sha256": hashlib.sha256(
            os.environ.get("XLA_FLAGS", "").encode()).hexdigest(),
    }


def fingerprint_mismatch(recorded: dict, current: dict | None = None
                         ) -> str | None:
    """None when ``recorded`` matches the current runtime; otherwise a
    human-readable reason (the structured-warning payload — a stale key
    must never load into the wrong runtime)."""
    current = current or runtime_fingerprint()
    for field in ("platform", "jax", "jaxlib", "xla_flags_sha256"):
        want, got = recorded.get(field), current.get(field)
        if want != got:
            return (f"aot {field} mismatch: artifact compiled for "
                    f"{want!r}, runtime is {got!r}")
    return None


# ---------------------------------------------------------------------------
# keying + pytree template codec (JSON-safe — no pickle in artifacts)
# ---------------------------------------------------------------------------

def _jsonable(obj):
    """Canonical JSON-safe form of a cache-key component: tuples/lists
    collapse to lists (both sides of the digest pass through this), scalars
    stay, everything else stringifies."""
    if isinstance(obj, (tuple, list)):
        return [_jsonable(x) for x in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return str(obj)


def aot_key_digest(fn_id: str, shape, dtype) -> str:
    """Stable cross-process digest of the (fn_id, bucket shape, dtype)
    portion of a CompiledCache key (the instance token is process-local and
    handled by ordinal binding instead)."""
    return hashlib.sha256(_canonical_json(
        [fn_id, _jsonable(shape), _jsonable(dtype)])).hexdigest()


def _encode_template(obj, counter: list) -> dict:
    """JSON template of a pytree built from dict/list/tuple/None/leaves,
    with leaf indices assigned in ``jax.tree_util.tree_flatten`` order
    (dicts traverse in sorted-key order). Raises TypeError on custom pytree
    nodes — those entries fall back to JIT."""
    if isinstance(obj, dict):
        keys = sorted(obj)
        return {"t": "d", "k": keys,
                "v": [_encode_template(obj[k], counter) for k in keys]}
    if isinstance(obj, (list, tuple)):
        return {"t": "l" if isinstance(obj, list) else "t",
                "v": [_encode_template(x, counter) for x in obj]}
    if obj is None:
        return {"t": "n"}
    idx = counter[0]
    counter[0] += 1
    return {"t": "x", "i": idx}


def _decode_template(template: dict, leaves):
    kind = template["t"]
    if kind == "d":
        return {k: _decode_template(v, leaves)
                for k, v in zip(template["k"], template["v"])}
    if kind in ("l", "t"):
        seq = [_decode_template(v, leaves) for v in template["v"]]
        return seq if kind == "l" else tuple(seq)
    if kind == "n":
        return None
    return leaves[template["i"]]


# ---------------------------------------------------------------------------
# publish-side capture
# ---------------------------------------------------------------------------

def _build_jittable(fn):
    """The one jit acquisition on the capture path: stage builders usually
    return a ``jax.jit`` wrapper directly (has ``.lower``); builders that
    return a closure *around* a jit (e.g. params partially applied) get
    re-wrapped so the closure's constants bake into the lowered module."""
    import jax

    return fn if hasattr(fn, "lower") else jax.jit(fn)


class AOTCapture:
    """Publish-time recorder installed on the CompiledCache via
    ``set_capture``: every miss built on the capturing thread is wrapped so
    its first call's concrete arguments are recorded next to the built jit;
    :meth:`export` then AOT-compiles and serializes each one."""

    def __init__(self):
        self._thread = threading.get_ident()
        self._ordinals: dict = {}
        self._records: list[dict] = []
        self._lock = threading.Lock()

    @property
    def tokens(self) -> list:
        """Every instance token seen (publish evicts their temporary
        executables from the process cache afterwards)."""
        return [t for t in self._ordinals if t is not None]

    def wrap(self, key: tuple, built):
        """Called by ``CompiledCache.get`` on a miss. Off-thread misses
        (a concurrent serve loop) pass through untouched — ordinal order
        must reflect only the warmup drive."""
        if threading.get_ident() != self._thread:
            return built
        fn_id, instance, shape, dtype = key
        with self._lock:
            ordinal = self._ordinals.setdefault(instance,
                                                len(self._ordinals))
            rec = {"fn_id": fn_id, "ordinal": ordinal, "shape": shape,
                   "dtype": dtype, "built": built, "call": None}
            self._records.append(rec)

        def wrapper(*args, **kwargs):
            if rec["call"] is None:
                rec["call"] = (args, kwargs)
            return built(*args, **kwargs)

        return wrapper

    def export(self, mechanism: str, put_blob) -> tuple[list[dict], list[dict]]:
        """AOT-compile + serialize every recorded entry. ``put_blob(bytes)
        -> sha256`` stores each executable (content-addressed next to the
        weights). Returns ``(entries, skipped)`` — a skip (donated buffers,
        custom pytree outputs, lowering failure) just means that shape JIT
        warms at load."""
        entries, skipped = [], []
        for rec in self._records:
            if rec["call"] is None:
                skipped.append({"fn_id": rec["fn_id"],
                                "shape": _jsonable(rec["shape"]),
                                "reason": "never invoked during capture"})
                continue
            try:
                entry, blob = _serialize_entry(rec, mechanism)
            except Exception as e:  # noqa: BLE001 - per-entry fallback
                skipped.append({"fn_id": rec["fn_id"],
                                "shape": _jsonable(rec["shape"]),
                                "reason": f"{type(e).__name__}: {e}"})
                continue
            entry["sha256"] = put_blob(blob)
            entry["bytes"] = len(blob)
            entries.append(entry)
        return entries, skipped


def _serialize_entry(rec: dict, mechanism: str) -> tuple[dict, bytes]:
    import jax
    from jax import tree_util as jtu

    args, kwargs = rec["call"]
    target = _build_jittable(rec["built"])
    lowered = target.lower(*args, **kwargs)
    if getattr(lowered, "donate_argnums", ()):
        raise AOTError("donated arguments cannot be AOT-served (the "
                       "executable would consume the caller's buffers)")
    entry = {
        "key": aot_key_digest(rec["fn_id"], rec["shape"], rec["dtype"]),
        "fn_id": rec["fn_id"],
        "ordinal": rec["ordinal"],
        "shape": _jsonable(rec["shape"]),
        "dtype": _jsonable(rec["dtype"]),
        "mechanism": mechanism,
    }
    if mechanism == "export":
        from jax import export as jexport

        exported = jexport.export(target)(*args, **kwargs)
        return entry, bytes(exported.serialize())
    compiled = lowered.compile()
    out = compiled(*args, **kwargs)
    counter = [0]
    template = _encode_template(out, counter)
    n_leaves = len(jtu.tree_leaves(out))
    if counter[0] != n_leaves:
        raise AOTError(f"output pytree has custom nodes ({n_leaves} leaves "
                       f"vs {counter[0]} template slots)")
    in_leaves = jtu.tree_leaves(lowered.in_avals)
    flat_args = jtu.tree_leaves((args, kwargs))
    if len(in_leaves) != len(flat_args):
        raise AOTError("input pytree has custom nodes or hoisted constants")
    entry["in_specs"] = [{"shape": [int(d) for d in a.shape],
                          "dtype": str(a.dtype)} for a in in_leaves]
    entry["out_template"] = template
    rexec = compiled.runtime_executable()
    return entry, bytes(rexec.client.serialize_executable(rexec))


def walk_stages(stage):
    """Deterministic pipeline-tree walk (root first, then nested ``stages``
    in order) — shared by the autotuner and anything needing one canonical
    stage order."""
    seen: set[int] = set()
    out = []

    def walk(obj):
        if obj is None or id(obj) in seen:
            return
        seen.add(id(obj))
        out.append(obj)
        getter = getattr(obj, "get", None)
        if callable(getter):
            try:
                children = getter("stages")
            except Exception:  # noqa: BLE001 - not every stage has 'stages'
                return
            if isinstance(children, (list, tuple)):
                for child in children:
                    walk(child)

    walk(stage)
    return out


def capture_stage_ladder(stage, rows, buckets, loop_cfg: dict,
                         put_blob) -> dict:
    """Drive ``stage`` through the serve-loop warmup at every ladder rung
    with capture on, then export+store the executables. Returns the
    manifest ``aot`` section. Graceful degradation: no mechanism -> a
    section with only a ``skipped`` note (loads fall back to JIT)."""
    mechanism = aot_mechanism()
    if mechanism is None:
        return {"entries": [], "skipped":
                [{"reason": "no executable-serialization mechanism in this "
                            "jax/jaxlib"}]}
    from ..core import batching as cb
    from ..io.serving import run_warmup

    cache = cb.get_compiled_cache()
    capture = AOTCapture()
    cache.set_capture(capture)
    try:
        run_warmup(stage, rows, list(buckets), loop_cfg)
    finally:
        cache.set_capture(None)
    entries, skipped = capture.export(mechanism, put_blob)
    # the captured executables were compiled against a throwaway reload of
    # the artifact — evict them so publish doesn't pin one dead copy of the
    # weights per publish
    for token in capture.tokens:
        cache.evict_instance(token)
    return {
        "mechanism": mechanism,
        "runtime": runtime_fingerprint(),
        "entries": entries,
        "skipped": skipped,
        "warmup": {"rows": list(rows),
                   "buckets": sorted(int(b) for b in buckets)},
        "total_bytes": sum(e["bytes"] for e in entries),
    }


# ---------------------------------------------------------------------------
# load-side second tier
# ---------------------------------------------------------------------------

def _build_xla_callable(blob: bytes, entry: dict):
    """Deserialize a raw XLA executable and wrap it behind the builder
    call convention: flatten live args, verify against the recorded input
    specs, execute, rebuild the recorded output pytree. No tracing, no
    compilation — the zero-cold-start path."""
    import jax
    from jax import tree_util as jtu

    client = jax.local_devices()[0].client
    rexec = client.deserialize_executable(bytes(blob), None)
    in_specs = [(tuple(s["shape"]), np.dtype(s["dtype"]))
                for s in entry["in_specs"]]
    template = entry["out_template"]

    def call(*args, **kwargs):
        flat = jtu.tree_leaves((args, kwargs))
        if len(flat) != len(in_specs):
            raise AOTError(
                f"aot executable {entry['fn_id']} expects "
                f"{len(in_specs)} arrays, got {len(flat)}")
        bufs = []
        for x, (shape, want) in zip(flat, in_specs):
            if isinstance(x, jax.Array) and tuple(x.shape) == shape \
                    and x.dtype == want:
                bufs.append(x)
                continue
            a = np.asarray(x)
            if tuple(a.shape) != shape:
                raise AOTError(
                    f"aot executable {entry['fn_id']} expects shape "
                    f"{shape}, got {tuple(a.shape)}")
            if a.dtype != want:
                a = a.astype(want)
            bufs.append(jax.device_put(a))
        return _decode_template(template, rexec.execute(bufs))

    return call


def _build_export_callable(blob: bytes, entry: dict):
    """``jax.export`` fallback: deserialization skips Python tracing of the
    original stage function; XLA still compiles once on first call (inside
    the one jit this builder owns)."""
    import jax
    from jax import export as jexport

    exported = jexport.deserialize(bytearray(blob))
    return jax.jit(exported.call)


class AOTExecutableSet:
    """The CompiledCache's persistent second tier for one loaded artifact.

    ``lookup`` runs on cache misses: entries match by (fn_id, shape, dtype)
    digest + the instance's first-seen ordinal (bound on the warmup thread,
    frozen afterwards). Blob reads are sha256-verified; a corrupt or
    missing blob demotes that entry to JIT with one structured warning —
    the swap itself always proceeds."""

    def __init__(self, aot_section: dict, blob_dir: str):
        self.mechanism = aot_section.get("mechanism")
        self.blob_dir = blob_dir
        self._by_key: dict[tuple, dict] = {}
        for e in aot_section.get("entries", ()):
            self._by_key[(e["key"], int(e["ordinal"]))] = e
        self._ordinals: dict = {}
        self._materialized: dict[tuple, object] = {}
        self._warned: set = set()
        self._bind_thread: int | None = None
        self._lock = threading.Lock()
        # load-report surface (the /admin/load warmup breakdown)
        self.hits = 0          # lookups served from a blob
        self.misses = 0        # lookups with no matching entry
        self.errors = 0        # blobs rejected (integrity/deserialize)
        self.loaded = 0        # distinct executables deserialized
        self.io_ms = 0.0       # wall spent reading + deserializing blobs

    def __len__(self) -> int:
        return len(self._by_key)

    def begin_binding(self) -> None:
        """Open the ordinal-binding window to the CURRENT thread (the
        warmup drive). Lookups from other threads see no entries until
        :meth:`freeze` — a concurrent serve loop on the old pipeline must
        not perturb first-seen ordering."""
        with self._lock:
            self._bind_thread = threading.get_ident()

    def freeze(self) -> None:
        """Close the binding window: known instances keep resolving from
        any thread; unknown instances fall back to tracing."""
        with self._lock:
            self._bind_thread = None

    def lookup(self, fn_id: str, instance, shape, dtype):
        with self._lock:
            if self._bind_thread is not None:
                if threading.get_ident() != self._bind_thread:
                    return None
                ordinal = self._ordinals.setdefault(instance,
                                                    len(self._ordinals))
            else:
                ordinal = self._ordinals.get(instance)
                if ordinal is None:
                    return None
        key = (aot_key_digest(fn_id, shape, dtype), ordinal)
        entry = self._by_key.get(key)
        if entry is None:
            with self._lock:
                self.misses += 1
            return None
        try:
            fn = self._load(key, entry)
        except Exception as e:  # noqa: BLE001 - a bad blob demotes to JIT
            with self._lock:
                self.errors += 1
                first = key not in self._warned
                self._warned.add(key)
            if first:
                logger.warning(json.dumps({
                    "event": "aot_blob_rejected", "fn_id": fn_id,
                    "sha256": entry.get("sha256"),
                    "error": f"{type(e).__name__}: {e}",
                    "action": "falling back to JIT trace for this entry"}))
            return None
        with self._lock:
            self.hits += 1
        return fn

    def _load(self, key: tuple, entry: dict):
        with self._lock:
            fn = self._materialized.get(key)
        if fn is not None:
            return fn
        t0 = time.perf_counter()
        path = os.path.join(self.blob_dir, entry["sha256"])
        with open(path, "rb") as f:
            blob = f.read()
        got = hashlib.sha256(blob).hexdigest()
        if got != entry["sha256"]:
            raise IntegrityError(
                f"aot blob {entry['sha256']} corrupt on read: bytes hash "
                f"to {got}")
        mechanism = entry.get("mechanism", self.mechanism)
        if mechanism == "xla":
            fn = _build_xla_callable(blob, entry)
        elif mechanism == "export":
            fn = _build_export_callable(blob, entry)
        else:
            raise AOTError(f"unknown aot mechanism {mechanism!r}")
        with self._lock:
            self._materialized[key] = fn
            self.loaded += 1
            self.io_ms += (time.perf_counter() - t0) * 1e3
        return fn

    def report(self) -> dict:
        with self._lock:
            return {"aot_hits": self.hits, "aot_misses": self.misses,
                    "aot_errors": self.errors,
                    "executables_loaded": self.loaded,
                    "io_ms": round(self.io_ms, 2),
                    "entries": len(self._by_key)}


def load_blocker(aot_section: dict) -> str | None:
    """Why this runtime cannot ride the artifact's AOT blobs (None = it
    can): fingerprint mismatch, mechanism unavailable here, or an artifact
    whose capture produced no entries."""
    if not aot_section.get("entries"):
        return "artifact has no aot entries"
    mechanism = aot_section.get("mechanism")
    available = aot_mechanism()
    if mechanism == "xla" and available != "xla":
        return (f"artifact uses the {mechanism!r} mechanism but this "
                f"runtime supports {available!r}")
    if mechanism == "export" and available is None:
        return "this runtime has no executable-serialization support"
    return fingerprint_mismatch(aot_section.get("runtime", {}))


def log_fallback(reason: str, model: str | None = None,
                 version: str | None = None) -> None:
    """ONE structured warning per fallback decision (the satellite fix: a
    platform/version mismatch must demote to JIT warmup loudly, never fail
    the swap)."""
    coarse = ("mismatch" if "mismatch" in reason
              else "disabled" if "disabled" in reason
              else "unsupported")
    _AOT_METRICS.get()["fallbacks"].inc(reason=coarse)
    logger.warning(json.dumps({
        "event": "aot_fallback", "model": model, "version": version,
        "reason": reason, "action": "JIT warmup (swap proceeds)"}))


def emit_load_metrics(breakdown: dict) -> None:
    """Mirror an /admin/load warmup breakdown into the synapseml_deploy_*
    series (PR-2 metrics registry)."""
    m = _AOT_METRICS.get()
    m["io_ms"].observe(float(breakdown.get("io_ms", 0.0)))
    m["compile_ms"].observe(float(breakdown.get("compile_ms", 0.0)))
    for field, handle in (("aot_hits", "aot_hits"),
                          ("aot_misses", "aot_misses"),
                          ("executables_loaded", "loaded"),
                          ("executables_traced", "traced")):
        n = int(breakdown.get(field, 0))
        if n:
            m[handle].inc(n)
