"""Content-addressed artifact store: sha256 blobs + signed JSON manifests.

The deployment unit here is a self-describing, versioned artifact (the TVM
lesson from PAPERS.md — compiled ML ships as artifacts, not live in-process
state), not a pickle handed to a worker at spawn time. The store owns three
invariants the registry (``registry/registry.py``) and the deployment plane
(``registry/deploy.py``) build on:

* **Content addressing** — every file of a published pipeline is stored once
  under ``blobs/<sha256>``; identical weights across versions dedupe for
  free, and a blob read re-hashes the bytes so silent corruption surfaces as
  :class:`IntegrityError`, never as a wrong prediction.
* **Atomicity** — every write (blob, manifest, alias pointer) goes through a
  same-directory temp file + ``os.replace``, so a crashed publish can never
  leave a half-written artifact that ``resolve()`` would load. The same
  helper (:func:`write_stream_verified`) backs
  ``models/downloader.ModelDownloader._fetch_to_file`` so checkpoint
  downloads and registry blobs cannot diverge in their torn-write handling.
* **Tamper evidence** — manifests are HMAC-SHA256 signed with a per-store
  key (``store.key``, created on first publish, 0600). Verification happens
  wherever the key is readable (the publishing side and local consumers);
  remote read-only consumers fall back to content addressing — every blob
  they fetch is digest-verified against the manifest they resolved.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import secrets
import shutil
from typing import Any, Callable

__all__ = [
    "IntegrityError",
    "ArtifactStore",
    "sha256_file",
    "atomic_write_bytes",
    "write_stream_verified",
]

_CHUNK = 1 << 20


class IntegrityError(RuntimeError):
    """Stored bytes do not match their recorded sha256 (or a manifest
    signature failed) — the artifact is corrupt or tampered with."""


# ---------------------------------------------------------------------------
# low-level atomic/verified file helpers (shared with models/downloader.py)
# ---------------------------------------------------------------------------

def sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(_CHUNK), b""):
            h.update(chunk)
    return h.hexdigest()


def _tmp_name(path: str) -> str:
    """Per-writer temp name: pid + thread id, so two THREADS of one process
    writing the same destination cannot interleave into one temp file and
    rename corrupt bytes under a verified name. ONE format, shared with the
    streamed writers (``io/files._tmp_path``) — the scoring sink's
    stale-temp sweep globs it."""
    from ..io.files import _tmp_path

    return _tmp_path(path)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write-then-rename in the destination directory (same filesystem, so
    ``os.replace`` is atomic); readers see the old file or the new file,
    never a torn one."""
    tmp = _tmp_name(path)
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def write_stream_verified(reader, path: str,
                          expected_sha256: str | None = None) -> str:
    """Stream ``reader`` (any object with ``.read(n)``) to ``path``
    atomically, hashing incrementally — one pass, constant memory. With
    ``expected_sha256`` the rename only happens on a digest match; a
    mismatch removes the temp file and raises :class:`IntegrityError`
    ("sha256 mismatch"), so a failed transfer never leaves a destination
    file at all. Returns the hex digest."""
    h = hashlib.sha256()
    tmp = _tmp_name(path)
    try:
        with open(tmp, "wb") as f:
            for chunk in iter(lambda: reader.read(_CHUNK), b""):
                h.update(chunk)
                f.write(chunk)
        got = h.hexdigest()
        if expected_sha256 and got != expected_sha256:
            raise IntegrityError(
                f"sha256 mismatch for {path!r}: expected {expected_sha256}, "
                f"got {got}")
        os.replace(tmp, path)
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    return got


def _canonical_json(obj: Any) -> bytes:
    """Stable byte form for hashing/signing (sorted keys, no whitespace
    drift)."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      default=str).encode()


def _safe_component(name: str) -> str:
    """Reject path-escaping names/versions/aliases (manifest and alias file
    names are caller data — the same untrusted-input guard as
    ``ModelDownloader._safe_path``)."""
    if (not name or name != os.path.basename(name) or name.startswith(".")
            or "/" in name or "\\" in name or os.path.isabs(name)):
        raise ValueError(f"unsafe registry path component: {name!r}")
    return name


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------

class ArtifactStore:
    """One directory owning blobs, manifests, and alias pointers.

    Layout under ``root``::

        blobs/<sha256>                  content-addressed files (dedup'd)
        manifests/<name>/<version>.json signed per-version manifests
        manifests/<name>/index.json     version list (remote listing)
        aliases/<name>/<alias>          pointer file: one version string
        store.key                       HMAC signing key (created lazily)

    Every path component is validated; every write is atomic; every blob
    read is digest-verified.
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    # -- paths -------------------------------------------------------------
    def blob_path(self, digest: str) -> str:
        if len(digest) != 64 or not all(c in "0123456789abcdef"
                                        for c in digest):
            raise ValueError(f"not a sha256 hex digest: {digest!r}")
        return os.path.join(self.root, "blobs", digest)

    def _manifest_dir(self, name: str) -> str:
        return os.path.join(self.root, "manifests", _safe_component(name))

    def manifest_path(self, name: str, version: str) -> str:
        return os.path.join(self._manifest_dir(name),
                            _safe_component(version) + ".json")

    def alias_path(self, name: str, alias: str) -> str:
        return os.path.join(self.root, "aliases", _safe_component(name),
                            _safe_component(alias))

    # -- blobs -------------------------------------------------------------
    def has_blob(self, digest: str) -> bool:
        return os.path.isfile(self.blob_path(digest))

    def put_blob_file(self, path: str) -> str:
        """Ingest a file; returns its digest. One streaming pass: hash
        while copying into a temp blob, then rename to the digest-named
        path (a multi-GB publish reads each file once, not twice).
        Already-present blobs are dropped, not rewritten (content
        addressing = free dedup across versions)."""
        blobs_dir = os.path.join(self.root, "blobs")
        os.makedirs(blobs_dir, exist_ok=True)
        tmp = _tmp_name(os.path.join(blobs_dir, ".ingest"))
        h = hashlib.sha256()
        try:
            with open(path, "rb") as src, open(tmp, "wb") as f:
                for chunk in iter(lambda: src.read(_CHUNK), b""):
                    h.update(chunk)
                    f.write(chunk)
            digest = h.hexdigest()
            dest = self.blob_path(digest)
            if os.path.isfile(dest):
                os.unlink(tmp)
            else:
                os.replace(tmp, dest)
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return digest

    def put_blob_bytes(self, data: bytes) -> str:
        digest = hashlib.sha256(data).hexdigest()
        dest = self.blob_path(digest)
        if not os.path.isfile(dest):
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            atomic_write_bytes(dest, data)
        return digest

    def get_blob(self, digest: str) -> bytes:
        """Read + verify; raises :class:`IntegrityError` on corruption."""
        with open(self.blob_path(digest), "rb") as f:
            data = f.read()
        got = hashlib.sha256(data).hexdigest()
        if got != digest:
            raise IntegrityError(
                f"blob {digest} corrupt on read: bytes hash to {got}")
        return data

    def materialize_blob(self, digest: str, dest: str) -> None:
        """Copy a blob to ``dest`` (creating parents), verifying the digest
        in the same streaming pass that writes the file."""
        os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
        with open(self.blob_path(digest), "rb") as f:
            write_stream_verified(f, dest, digest)

    def ingest_tree(self, src_dir: str) -> list[dict]:
        """Blobify every file under ``src_dir``; returns the manifest
        ``files`` list: ``[{"path": rel, "sha256": d, "bytes": n}, ...]``
        sorted by path (deterministic manifests)."""
        files = []
        for dirpath, _dirnames, filenames in os.walk(src_dir):
            for fname in filenames:
                full = os.path.join(dirpath, fname)
                rel = os.path.relpath(full, src_dir)
                digest = self.put_blob_file(full)
                files.append({"path": rel.replace(os.sep, "/"),
                              "sha256": digest,
                              "bytes": os.path.getsize(full)})
        files.sort(key=lambda e: e["path"])
        return files

    def materialize_tree(self, files: list[dict], dest_dir: str,
                         fetch: Callable[[str, str], None] | None = None
                         ) -> str:
        """Rebuild a published directory tree from its manifest ``files``
        list. ``fetch(digest, dest_path)`` overrides the blob source (the
        remote registry passes an HTTP fetcher); default reads local blobs.
        Builds into a temp dir and renames, so a partially-materialized tree
        is never visible."""
        tmp = f"{dest_dir}.building.{os.getpid()}"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        try:
            root = os.path.realpath(tmp)
            for entry in files:
                dest = os.path.realpath(os.path.join(tmp, entry["path"]))
                if not dest.startswith(root + os.sep):
                    raise ValueError(
                        f"manifest path escapes the tree: {entry['path']!r}")
                if fetch is not None:
                    os.makedirs(os.path.dirname(dest), exist_ok=True)
                    fetch(entry["sha256"], dest)
                else:
                    self.materialize_blob(entry["sha256"], dest)
            shutil.rmtree(dest_dir, ignore_errors=True)
            os.replace(tmp, dest_dir)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        return dest_dir

    # -- signing -----------------------------------------------------------
    def _key(self, create: bool = False) -> bytes | None:
        path = os.path.join(self.root, "store.key")
        try:
            with open(path, "rb") as f:
                return f.read()
        except OSError:
            if not create:
                return None
        key = secrets.token_bytes(32)
        tmp = f"{path}.tmp.{os.getpid()}"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        try:
            os.write(fd, key)
        finally:
            os.close(fd)
        os.replace(tmp, path)
        return key

    def sign(self, manifest: dict) -> str:
        body = {k: v for k, v in manifest.items() if k != "signature"}
        return hmac.new(self._key(create=True), _canonical_json(body),
                        hashlib.sha256).hexdigest()

    def verify_signature(self, manifest: dict) -> bool:
        """True when the signature checks out; :class:`IntegrityError` when
        it does not; False when no key is readable (remote consumer —
        content addressing still verifies every blob)."""
        key = self._key(create=False)
        if key is None:
            return False
        body = {k: v for k, v in manifest.items() if k != "signature"}
        want = hmac.new(key, _canonical_json(body), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, manifest.get("signature", "")):
            raise IntegrityError(
                f"manifest signature mismatch for "
                f"{manifest.get('name')}/{manifest.get('version')}")
        return True

    # -- manifests ---------------------------------------------------------
    def write_manifest(self, name: str, version: str, manifest: dict) -> str:
        path = self.manifest_path(name, version)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        signed = dict(manifest)
        signed["signature"] = self.sign(manifest)
        atomic_write_bytes(path, json.dumps(signed, indent=2,
                                            default=str).encode())
        # keep the remote-listable version index in sync (atomic rewrite)
        index = sorted(set(self.list_versions(name)) | {version},
                       key=_version_sort_key)
        atomic_write_bytes(os.path.join(self._manifest_dir(name),
                                        "index.json"),
                           json.dumps(index).encode())
        return path

    def read_manifest(self, name: str, version: str,
                      verify: bool = True) -> dict:
        with open(self.manifest_path(name, version)) as f:
            manifest = json.load(f)
        if verify:
            self.verify_signature(manifest)
        return manifest

    def list_versions(self, name: str) -> list[str]:
        try:
            entries = os.listdir(self._manifest_dir(name))
        except OSError:
            return []
        return sorted((e[:-len(".json")] for e in entries
                       if e.endswith(".json") and e != "index.json"),
                      key=_version_sort_key)

    def list_models(self) -> list[str]:
        try:
            return sorted(os.listdir(os.path.join(self.root, "manifests")))
        except OSError:
            return []

    # -- garbage collection ------------------------------------------------
    def referenced_blobs(self) -> set[str]:
        """Every blob digest any manifest references: the stage-tree
        ``files`` plus AOT executable ``entries``. Aliases point at
        versions, so their references are already covered by the version
        manifests."""
        refs: set[str] = set()
        for name in self.list_models():
            for version in self.list_versions(name):
                try:
                    manifest = self.read_manifest(name, version,
                                                  verify=False)
                except (OSError, json.JSONDecodeError):
                    continue  # unreadable manifest: prune nothing it names
                for entry in manifest.get("files", ()):
                    refs.add(entry.get("sha256"))
                for entry in (manifest.get("aot") or {}).get("entries", ()):
                    refs.add(entry.get("sha256"))
        refs.discard(None)
        return refs

    def gc(self, dry_run: bool = False, min_age_s: float = 3600.0) -> dict:
        """Prune blobs unreferenced by any manifest (orphans from failed
        publishes accumulate forever; AOT executable ladders multiply
        store size, so dead versions now leave real garbage).

        ``dry_run=True`` reports without deleting. ``min_age_s`` protects
        blobs younger than the window — a concurrent publish writes blobs
        BEFORE its manifest, and gc must never eat an in-flight publish's
        blobs. Returns ``{"scanned", "referenced", "pruned",
        "bytes_freed", "kept_young", "dry_run"}``."""
        import time

        blobs_dir = os.path.join(self.root, "blobs")
        try:
            names = sorted(os.listdir(blobs_dir))
        except OSError:
            names = []
        refs = self.referenced_blobs()
        now = time.time()
        pruned: list[str] = []
        bytes_freed = 0
        kept_young = 0
        scanned = 0
        for fname in names:
            if len(fname) != 64 or not all(c in "0123456789abcdef"
                                           for c in fname):
                continue  # temp files belong to the writers' cleanup
            scanned += 1
            if fname in refs:
                continue
            path = os.path.join(blobs_dir, fname)
            try:
                st = os.stat(path)
            except OSError:
                continue
            if now - st.st_mtime < min_age_s:
                kept_young += 1
                continue
            pruned.append(fname)
            bytes_freed += st.st_size
            if not dry_run:
                try:
                    os.unlink(path)
                except OSError:
                    pass
        return {"scanned": scanned, "referenced": len(refs),
                "pruned": pruned, "bytes_freed": bytes_freed,
                "kept_young": kept_young, "dry_run": dry_run}

    # -- aliases (atomically-swapped pointer files) ------------------------
    def write_alias(self, name: str, alias: str, version: str) -> None:
        path = self.alias_path(name, alias)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        atomic_write_bytes(path, _safe_component(version).encode())

    def read_alias(self, name: str, alias: str) -> str | None:
        try:
            with open(self.alias_path(name, alias)) as f:
                return f.read().strip() or None
        except OSError:
            return None

    def list_aliases(self, name: str) -> dict[str, str]:
        d = os.path.join(self.root, "aliases", _safe_component(name))
        try:
            entries = os.listdir(d)
        except OSError:
            return {}
        out = {}
        for alias in sorted(entries):
            target = self.read_alias(name, alias)
            if target:
                out[alias] = target
        return out


def _version_sort_key(version: str):
    """``v2`` sorts before ``v10`` (numeric when the conventional form
    matches, lexicographic otherwise)."""
    if version.startswith("v") and version[1:].isdigit():
        return (0, int(version[1:]), version)
    return (1, 0, version)
