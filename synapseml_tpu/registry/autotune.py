"""Publish-time backend autotuning: pin each artifact's fastest kernels.

The second half of the TVM lesson (PAPERS.md, arXiv:1802.04799): kernel
*selection* is a compile-time search, so run it once at ``registry.publish``
and record the verdict in the manifest — ``deploy.py`` / ``/admin/load``
then pin the winners at load instead of trusting hardcoded defaults.

The search harness is the same measurement discipline the standing
``benchmarks/attn_backends.py`` / ``benchmarks/gbdt_hist_backends.py``
decision benches use — per-candidate timing on the real stage at each
ladder rung, warm-first then min-of-N — applied to the stage being
published: any stage class declaring ``_AUTOTUNE_PARAMS = {"param":
(candidates...)}`` gets each candidate timed through the serve-loop warmup
drive (``io.serving.run_warmup``) at every bucket rung, and the winner per
``(platform, rung)`` lands in the manifest's ``autotune`` section.

Backends whose cost lives outside the transform path (e.g. the GBDT
``histogram_impl`` — a *training*-time kernel the hist-backends bench
decides) feed in through ``winners`` overrides: pass the bench's verdict to
``publish(autotune={"winners": {...}})`` and the load path pins it the same
way. Winners only apply on the platform they were measured on — a manifest
tuned on TPU loading into a CPU worker keeps the stage's saved defaults.
"""

from __future__ import annotations

import logging
import time

from ..core.params import Param
from .aot import walk_stages

__all__ = ["autotune_stage", "apply_autotune", "tunable_params"]

logger = logging.getLogger("synapseml_tpu.registry.autotune")


def tunable_params(stage) -> list[tuple]:
    """``(stage_obj, param_name, candidates)`` for every tunable the
    pipeline tree declares via ``_AUTOTUNE_PARAMS``."""
    out = []
    for st in walk_stages(stage):
        declared = getattr(type(st), "_AUTOTUNE_PARAMS", None)
        if not declared:
            continue
        for param, candidates in declared.items():
            if isinstance(getattr(type(st), param, None), Param):
                out.append((st, param, tuple(candidates)))
    return out


def _time_rung(stage, rows, rung, loop_cfg, trials: int) -> float:
    """min-of-``trials`` wall for one warmup drive at one rung, after one
    untimed warm pass (the first call traces/compiles; kernel choice is
    about steady-state serving, same discipline as the decision benches).
    Rows are cycled to EXACTLY the rung size so the drive transforms one
    rung-sized batch and nothing else — ``run_warmup`` would otherwise
    union a second ``len(rows)``-sized batch into every timing."""
    from ..io.serving import run_warmup

    bodies = [rows[i % len(rows)] for i in range(int(rung))]
    run_warmup(stage, bodies, [rung], loop_cfg)
    best = float("inf")
    for _ in range(max(trials, 1)):
        t0 = time.perf_counter()
        run_warmup(stage, bodies, [rung], loop_cfg)
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def autotune_stage(stage, rows, buckets, loop_cfg: dict,
                   trials: int = 2, winners: dict | None = None,
                   platform: str | None = None) -> dict | None:
    """Search every declared tunable over ``buckets`` and mutate ``stage``
    to the winners (the AOT capture that follows compiles the winning
    kernels). Returns the manifest ``autotune`` section, or None when
    there is nothing to record. Candidates that fail to run are skipped
    with their error recorded — a broken backend can never win."""
    from ..core import batching as cb

    if platform is None:
        import jax

        platform = jax.default_backend()
    tunables = tunable_params(stage) if rows else []
    if not tunables and not winners:
        return None
    section = {"platform": platform, "winners": dict(winners or {}),
               "per_rung": {}, "timings_ms": {}, "errors": {}}
    rungs = sorted({int(b) for b in buckets}) or [1]
    for st, param, candidates in tunables:
        original = st.get(param)
        timings: dict[str, dict] = {}
        errors: dict[str, str] = {}
        for cand in candidates:
            st.set(**{param: cand})
            cb.invalidate_token(st)
            per_rung = {}
            try:
                for rung in rungs:
                    per_rung[str(rung)] = round(
                        _time_rung(stage, rows, rung, loop_cfg, trials), 3)
            except Exception as e:  # noqa: BLE001 - a broken backend loses
                errors[str(cand)] = f"{type(e).__name__}: {e}"
                continue
            timings[str(cand)] = per_rung
        if not timings:
            # every candidate failed: restore the stage's original value —
            # the AOT capture that follows must not compile (and the
            # manifest must not omit) a backend the search left behind
            st.set(**{param: original})
            cb.invalidate_token(st)
            section["errors"][param] = errors
            continue
        # winner per rung, overall = lowest summed wall across the ladder
        per_rung_winners = {
            str(r): min(timings, key=lambda c: timings[c][str(r)])
            for r in rungs}
        winner = min(timings, key=lambda c: sum(timings[c].values()))
        st.set(**{param: winner})
        cb.invalidate_token(st)
        section["winners"][param] = winner
        section["per_rung"][param] = per_rung_winners
        section["timings_ms"][param] = timings
        if errors:
            section["errors"][param] = errors
    if not section["winners"]:
        return None
    if not section["errors"]:
        del section["errors"]
    return section


def apply_autotune(stage, section: dict,
                   platform: str | None = None) -> list[dict]:
    """Pin a manifest's autotuned winners onto a freshly loaded stage tree
    (called by ``/admin/load`` before warmup/AOT binding). Only applies on
    the platform the search ran on; returns the list of applied changes."""
    if platform is None:
        import jax

        platform = jax.default_backend()
    if not section or section.get("platform") != platform:
        return []
    from ..core import batching as cb

    applied = []
    winners = section.get("winners") or {}
    for st in walk_stages(stage):
        for param, winner in winners.items():
            if not isinstance(getattr(type(st), param, None), Param):
                continue
            before = st.get(param)
            if before == winner:
                continue
            st.set(**{param: winner})
            cb.invalidate_token(st)
            applied.append({"stage": type(st).__name__, "param": param,
                            "from": before, "to": winner})
    return applied
