"""``python -m synapseml_tpu`` — environment self-test.

Answers "does this install work on this machine" in under a minute: backend
and mesh detection, a GBDT fit/score, a text-classifier train step, an
ONNX conversion round trip, and the native library build — each reported
PASS/FAIL with the failure captured instead of a stack-trace bail (mirrors
the role of the reference's notebook smoke tier for cluster validation).
"""

from __future__ import annotations

import sys
import time


def _check(name: str, fn, report: list) -> None:
    t0 = time.perf_counter()
    try:
        detail = fn() or ""
        report.append((name, True, f"{time.perf_counter() - t0:.1f}s", detail))
    except Exception as e:  # noqa: BLE001 — the point is the report
        report.append((name, False, f"{time.perf_counter() - t0:.1f}s",
                       f"{type(e).__name__}: {e}"))


def _backend_responsive(timeout_s: float = 75.0) -> bool:
    """Probe default-backend init in a SUBPROCESS under a timeout.

    The axon TPU relay can wedge so hard that even ``jax.devices()`` never
    returns, and once a process is stuck in that C call it cannot be
    un-hung — so the probe must burn a child process, not a thread."""
    import subprocess
    import sys as _sys

    try:
        return subprocess.run(
            [_sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, capture_output=True).returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


def selftest(argv: list[str] | None = None) -> int:
    import numpy as np

    argv = list(sys.argv[1:] if argv is None else argv)
    if "--help" in argv or "-h" in argv:
        print("usage: synapseml-tpu-selftest [--cpu]\n\n"
              "Environment self-test: backend, mesh, GBDT, text classifier,\n"
              "ONNX registry, native build — each reported PASS/FAIL.\n\n"
              "  --cpu   skip the accelerator probe and run on CPU")
        return 0

    import jax

    if "--cpu" in argv:
        jax.config.update("jax_platforms", "cpu")
    elif not _backend_responsive():
        print("default backend unresponsive (relay down?) — "
              "falling back to CPU\n")
        jax.config.update("jax_platforms", "cpu")

    report: list = []

    def backend():
        import jax

        devs = jax.devices()
        return f"{devs[0].platform} x{len(devs)}"

    def mesh():
        from .parallel import MeshConfig, create_mesh

        m = create_mesh(MeshConfig(data=-1))
        return f"axes={m.axis_sizes}"

    def gbdt():
        from .core import DataFrame
        from .gbdt import LightGBMClassifier

        rs = np.random.default_rng(0)
        X = rs.normal(size=(400, 6)).astype(np.float32)
        y = (X @ rs.normal(size=6) > 0).astype(np.int32)
        df = DataFrame.from_dict({"features": X, "label": y})
        model = LightGBMClassifier(num_iterations=5, num_leaves=7,
                                   max_bin=63).fit(df)
        acc = float(np.mean(model.transform(df).collect_column("prediction") == y))
        assert acc > 0.7, f"accuracy {acc}"
        return f"train acc {acc:.2f}"

    def text():
        from .core import DataFrame
        from .models import DeepTextClassifier

        df = DataFrame.from_rows([{"text": "good great", "label": 1},
                                  {"text": "bad awful", "label": 0}] * 8)
        model = DeepTextClassifier(checkpoint="bert-tiny", num_classes=2,
                                   batch_size=8, max_token_len=16,
                                   max_steps=4, learning_rate=3e-3).fit(df)
        out = model.transform(df)
        return f"{out.count()} rows scored"

    def onnx():
        from .onnx import ONNXModel
        from .onnx.convert import OP_REGISTRY

        assert len(OP_REGISTRY) > 130
        return f"{len(OP_REGISTRY)} ops registered"

    def native():
        from . import native as nat

        return "built" if nat.available() else "pure-python fallback"

    _check("jax backend", backend, report)
    _check("device mesh", mesh, report)
    _check("gbdt fit/score", gbdt, report)
    _check("text classifier", text, report)
    _check("onnx registry", onnx, report)
    _check("native library", native, report)

    width = max(len(n) for n, *_ in report)
    failures = 0
    for name, ok, took, detail in report:
        status = "PASS" if ok else "FAIL"
        failures += 0 if ok else 1
        print(f"{name.ljust(width)}  {status}  {took:>6}  {detail}")
    print(f"{'-' * (width + 20)}\n"
          f"{len(report) - failures}/{len(report)} checks passed")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(selftest(sys.argv[1:]))
