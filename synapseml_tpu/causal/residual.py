"""ResidualTransformer (reference ``causal/ResidualTransformer.scala``):
residual column = observed - predicted (class-1 probability when the
prediction column holds probability vectors)."""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param, TypeConverters
from ..core.pipeline import Transformer

__all__ = ["ResidualTransformer"]


class ResidualTransformer(Transformer):
    feature_name = "causal"

    observed_col = Param("observed_col", "observed outcome column", default="label")
    predicted_col = Param("predicted_col", "prediction column", default="prediction")
    output_col = Param("output_col", "residual column", default="residual")
    class_index = Param("class_index", "probability index when predictions are vectors",
                        default=1, converter=TypeConverters.to_int)

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("observed_col"), self.get("predicted_col"))

        def resid(p):
            obs = np.asarray(p[self.get("observed_col")], np.float64)
            pred = p[self.get("predicted_col")]
            if pred.dtype == object or (hasattr(pred[0], "__len__")
                                        and not np.isscalar(pred[0])):
                arr = np.stack([np.atleast_1d(np.asarray(v, np.float64)) for v in pred])
                idx = min(self.get("class_index"), arr.shape[1] - 1)
                pred = arr[:, idx]
            else:
                pred = np.asarray(pred, np.float64)
            return obs - pred

        return df.with_column(self.get("output_col"), resid)
