"""Double machine learning (reference ``causal/DoubleMLEstimator.scala:63``,
``OrthoForestDMLEstimator.scala:31``).

DoubleML: cross-fitted partially-linear model. Per sample-split iteration:
fit outcome model E[Y|X] and treatment model E[T|X] on fold A, residualize
fold B (and vice versa), then ATE = sum(res_t * res_y) / sum(res_t^2) over
the residualized data. Repeated over ``max_iter`` random splits; the final
ATE is the median (the reference averages percentiles) and the confidence
interval comes from the percentile distribution of per-split estimates.

OrthoForestDML: heterogeneous (per-row) effects — residualize exactly like
DML, then fit a depth-limited regression tree on heterogeneity features where
each leaf's value is the local ratio sum(res_t*res_y)/sum(res_t^2).
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model, Transformer

__all__ = ["DoubleMLEstimator", "DoubleMLModel",
           "OrthoForestDMLEstimator", "OrthoForestDMLModel"]


def _predictions(model: Transformer, df: DataFrame, pred_col_hint: str | None = None) -> np.ndarray:
    scored = model.transform(df)
    for col in ([pred_col_hint] if pred_col_hint else []) + ["probability", "prediction"]:
        if col and col in scored.columns:
            vals = scored.collect_column(col)
            if vals.dtype == object or (len(vals) and hasattr(vals[0], "__len__")):
                arr = np.stack([np.atleast_1d(np.asarray(v, np.float64)) for v in vals])
                return arr[:, -1] if arr.shape[1] > 1 else arr[:, 0]
            return np.asarray(vals, np.float64)
    raise ValueError(f"no prediction column found in {scored.columns}")


def _residualize(df: DataFrame, outcome_model_est, treatment_model_est,
                 outcome_col: str, treatment_col: str, folds: tuple,
                 pred_col: str | None) -> tuple[np.ndarray, np.ndarray]:
    """Cross-fit: model trained on the other fold predicts this fold."""
    y = np.asarray(df.collect_column(outcome_col), np.float64)
    t = np.asarray(df.collect_column(treatment_col), np.float64)
    whole = df.collect()
    res_y = np.zeros_like(y)
    res_t = np.zeros_like(t)
    for fold_idx, other_idx in (folds, folds[::-1]):
        train = DataFrame([{k: v[other_idx] for k, v in whole.items()}])
        test = DataFrame([{k: v[fold_idx] for k, v in whole.items()}])
        om = outcome_model_est.copy().fit(train)
        tm = treatment_model_est.copy().fit(train)
        res_y[fold_idx] = y[fold_idx] - _predictions(om, test, pred_col)
        res_t[fold_idx] = t[fold_idx] - _predictions(tm, test, pred_col)
    return res_y, res_t


class DoubleMLEstimator(Estimator):
    """(ref ``DoubleMLEstimator.scala:63``)"""

    feature_name = "causal"

    outcome_model = ComplexParam("outcome_model", "estimator for E[Y|X]")
    treatment_model = ComplexParam("treatment_model", "estimator for E[T|X]")
    outcome_col = Param("outcome_col", "outcome column", default="outcome")
    treatment_col = Param("treatment_col", "treatment column", default="treatment")
    max_iter = Param("max_iter", "number of sample-splitting repetitions",
                     default=1, converter=TypeConverters.to_int)
    confidence_level = Param("confidence_level", "CI level", default=0.975,
                             converter=TypeConverters.to_float)
    prediction_col = Param("prediction_col", "nuisance models' output column",
                           default=None)
    seed = Param("seed", "rng seed", default=0, converter=TypeConverters.to_int)

    def _fit(self, df: DataFrame) -> "DoubleMLModel":
        self.require_columns(df, self.get("outcome_col"), self.get("treatment_col"))
        n = df.count()
        rng = np.random.default_rng(self.get("seed"))
        estimates = []
        for _ in range(self.get("max_iter")):
            perm = rng.permutation(n)
            half = n // 2
            folds = (np.sort(perm[:half]), np.sort(perm[half:]))
            res_y, res_t = _residualize(
                df, self.get("outcome_model"), self.get("treatment_model"),
                self.get("outcome_col"), self.get("treatment_col"), folds,
                self.get("prediction_col"))
            denom = float(res_t @ res_t)
            if denom < 1e-12:
                continue
            estimates.append(float(res_t @ res_y) / denom)
        if not estimates:
            raise RuntimeError("DoubleML: treatment residuals are all ~0 "
                               "(treatment fully predictable from confounders?)")
        estimates = np.asarray(estimates)
        level = self.get("confidence_level")
        lo, hi = (np.percentile(estimates, [(1 - level) * 100, level * 100])
                  if len(estimates) > 1 else (estimates[0], estimates[0]))
        return DoubleMLModel(ate=float(np.median(estimates)),
                             ci=[float(lo), float(hi)],
                             raw_estimates=estimates.tolist())


class DoubleMLModel(Model):
    ate = Param("ate", "average treatment effect", converter=TypeConverters.to_float)
    ci = ComplexParam("ci", "[low, high] percentile confidence interval")
    raw_estimates = ComplexParam("raw_estimates", "per-split ATE estimates")

    def get_avg_treatment_effect(self) -> float:
        return self.get("ate")

    def get_confidence_interval(self) -> list:
        return list(self.get("ci"))

    def _transform(self, df: DataFrame) -> DataFrame:
        return df.with_column("effect",
                              lambda p: np.full(len(next(iter(p.values()))),
                                                self.get("ate")))


# ---------------------------------------------------------------------------
# Ortho forest (heterogeneous effects)
# ---------------------------------------------------------------------------

def _grow_effect_tree(H: np.ndarray, res_y: np.ndarray, res_t: np.ndarray,
                      max_depth: int, min_leaf: int):
    """Regression tree on heterogeneity features H; leaf value = local DML
    ratio. Split criterion: maximize variance of the child effects."""
    feature, threshold, left, right, value = [], [], [], [], []

    def effect(idx):
        denom = float(res_t[idx] @ res_t[idx])
        return float(res_t[idx] @ res_y[idx]) / denom if denom > 1e-12 else 0.0

    def grow(idx: np.ndarray, depth: int) -> int:
        node = len(feature)
        feature.append(-1)
        threshold.append(0.0)
        left.append(-1)
        right.append(-1)
        value.append(effect(idx))
        if depth >= max_depth or len(idx) < 2 * min_leaf:
            return node
        best = None
        for f in range(H.shape[1]):
            vals = H[idx, f]
            for q in np.quantile(vals, [0.25, 0.5, 0.75]):
                lmask = vals <= q
                nl, nr = int(lmask.sum()), int((~lmask).sum())
                if nl < min_leaf or nr < min_leaf:
                    continue
                el, er = effect(idx[lmask]), effect(idx[~lmask])
                score = nl * nr * (el - er) ** 2
                if best is None or score > best[0]:
                    best = (score, f, q, lmask)
        if best is None or best[0] <= 0:
            return node
        _, f, q, lmask = best
        feature[node] = f
        threshold[node] = float(q)
        left[node] = grow(idx[lmask], depth + 1)
        right[node] = grow(idx[~lmask], depth + 1)
        return node

    grow(np.arange(len(H)), 0)
    return (np.asarray(feature, np.int32), np.asarray(threshold, np.float64),
            np.asarray(left, np.int32), np.asarray(right, np.int32),
            np.asarray(value, np.float64))


def _tree_predict(H: np.ndarray, tree) -> np.ndarray:
    feature, threshold, left, right, value = tree
    node = np.zeros(len(H), np.int32)
    active = feature[node] >= 0
    while np.any(active):
        rows = np.nonzero(active)[0]
        cur = node[rows]
        go_left = H[rows, feature[cur]] <= threshold[cur]
        node[rows] = np.where(go_left, left[cur], right[cur])
        active = feature[node] >= 0
    return value[node]


class OrthoForestDMLEstimator(Estimator):
    """(ref ``OrthoForestDMLEstimator.scala:31``)"""

    feature_name = "causal"

    outcome_model = ComplexParam("outcome_model", "estimator for E[Y|X]")
    treatment_model = ComplexParam("treatment_model", "estimator for E[T|X]")
    outcome_col = Param("outcome_col", "outcome column", default="outcome")
    treatment_col = Param("treatment_col", "treatment column", default="treatment")
    heterogeneity_cols = ComplexParam("heterogeneity_cols",
                                      "columns the effect may vary over")
    num_trees = Param("num_trees", "trees in the effect forest", default=20,
                      converter=TypeConverters.to_int)
    max_depth = Param("max_depth", "effect tree depth", default=3,
                      converter=TypeConverters.to_int)
    min_samples_leaf = Param("min_samples_leaf", "min rows per leaf", default=10,
                             converter=TypeConverters.to_int)
    output_col = Param("output_col", "per-row effect column", default="effect")
    prediction_col = Param("prediction_col", "nuisance models' output column",
                           default=None)
    seed = Param("seed", "rng seed", default=0, converter=TypeConverters.to_int)

    def _fit(self, df: DataFrame) -> "OrthoForestDMLModel":
        hcols = self.get("heterogeneity_cols")
        self.require_columns(df, self.get("outcome_col"), self.get("treatment_col"),
                             *hcols)
        n = df.count()
        rng = np.random.default_rng(self.get("seed"))
        perm = rng.permutation(n)
        half = n // 2
        folds = (np.sort(perm[:half]), np.sort(perm[half:]))
        res_y, res_t = _residualize(
            df, self.get("outcome_model"), self.get("treatment_model"),
            self.get("outcome_col"), self.get("treatment_col"), folds,
            self.get("prediction_col"))
        H = np.stack([np.asarray(df.collect_column(c), np.float64) for c in hcols],
                     axis=1)
        trees = []
        for _ in range(self.get("num_trees")):
            idx = rng.integers(0, n, n)  # bootstrap
            trees.append(_grow_effect_tree(H[idx], res_y[idx], res_t[idx],
                                           self.get("max_depth"),
                                           self.get("min_samples_leaf")))
        return OrthoForestDMLModel(trees=trees, heterogeneity_cols=list(hcols),
                                   output_col=self.get("output_col"))


class OrthoForestDMLModel(Model):
    trees = ComplexParam("trees", "effect forest (flat arrays)")
    heterogeneity_cols = ComplexParam("heterogeneity_cols", "effect feature columns")
    output_col = Param("output_col", "per-row effect column", default="effect")

    def _transform(self, df: DataFrame) -> DataFrame:
        hcols = self.get("heterogeneity_cols")
        self.require_columns(df, *hcols)

        def per_part(p):
            H = np.stack([np.asarray(p[c], np.float64) for c in hcols], axis=1)
            preds = np.mean([_tree_predict(H, t) for t in self.get("trees")], axis=0)
            q = dict(p)
            q[self.get("output_col")] = preds
            return q

        return df.map_partitions(per_part)
