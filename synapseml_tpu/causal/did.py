"""Difference-in-differences family (reference ``causal/DiffInDiffEstimator``,
``SyntheticControlEstimator``, ``SyntheticDiffInDiffEstimator:28``).

DiD: OLS with the interaction term Y ~ treat + post + treat*post; the
interaction coefficient is the effect, its OLS standard error is reported.

Synthetic control: simplex unit weights fitted on pre-period control outcomes
to match the treated pre-trajectory (``constrained_least_squares``); effect =
post-period treated mean minus synthetic-control mean.

Synthetic DiD: unit AND time simplex weights (both with ridge + intercept per
Arkhangelsky et al.), effect from the weighted DiD regression.
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param, TypeConverters
from ..core.pipeline import Estimator, Model
from .opt import constrained_least_squares

__all__ = ["DiffInDiffEstimator", "SyntheticControlEstimator",
           "SyntheticDiffInDiffEstimator", "DiffInDiffModel"]


class DiffInDiffModel(Model):
    treatment_effect = Param("treatment_effect", "estimated effect",
                             converter=TypeConverters.to_float)
    standard_error = Param("standard_error", "OLS standard error", default=None)
    unit_weights = Param("unit_weights", "synthetic control unit weights",
                         default=None)
    time_weights = Param("time_weights", "synthetic DiD time weights", default=None)

    def get_treatment_effect(self) -> float:
        return self.get("treatment_effect")

    def _transform(self, df: DataFrame) -> DataFrame:
        return df.with_column(
            "effect", lambda p: np.full(len(next(iter(p.values()))),
                                        self.get("treatment_effect")))


class _DiDBase(Estimator):
    outcome_col = Param("outcome_col", "outcome column", default="outcome")
    treatment_col = Param("treatment_col", "treatment-group indicator", default="treatment")
    post_treatment_col = Param("post_treatment_col", "post-period indicator",
                               default="postTreatment")


class DiffInDiffEstimator(_DiDBase):
    """(ref ``DiffInDiffEstimator.scala``)"""

    feature_name = "causal"

    def _fit(self, df: DataFrame) -> DiffInDiffModel:
        self.require_columns(df, self.get("outcome_col"), self.get("treatment_col"),
                             self.get("post_treatment_col"))
        y = np.asarray(df.collect_column(self.get("outcome_col")), np.float64)
        t = np.asarray(df.collect_column(self.get("treatment_col")), np.float64)
        s = np.asarray(df.collect_column(self.get("post_treatment_col")), np.float64)
        X = np.stack([np.ones_like(y), t, s, t * s], axis=1)
        coef, *_ = np.linalg.lstsq(X, y, rcond=None)
        resid = y - X @ coef
        dof = max(len(y) - X.shape[1], 1)
        sigma2 = float(resid @ resid) / dof
        cov = sigma2 * np.linalg.inv(X.T @ X)
        return DiffInDiffModel(treatment_effect=float(coef[3]),
                               standard_error=float(np.sqrt(cov[3, 3])))


class SyntheticControlEstimator(_DiDBase):
    """(ref ``SyntheticControlEstimator.scala``) — panel data: unit_col x
    time_col grid; exactly one treated unit group, treatment starts when
    post_treatment_col flips to 1."""

    feature_name = "causal"

    unit_col = Param("unit_col", "panel unit id column", default="unit")
    time_col = Param("time_col", "panel time column", default="time")
    ridge = Param("ridge", "weight-solver ridge", default=1e-6,
                  converter=TypeConverters.to_float)

    def _panel(self, df: DataFrame):
        units = np.asarray(df.collect_column(self.get("unit_col")))
        times = np.asarray(df.collect_column(self.get("time_col")))
        y = np.asarray(df.collect_column(self.get("outcome_col")), np.float64)
        treat = np.asarray(df.collect_column(self.get("treatment_col")), np.float64)
        post = np.asarray(df.collect_column(self.get("post_treatment_col")), np.float64)
        u_levels, u_idx = np.unique(units, return_inverse=True)
        t_levels, t_idx = np.unique(times, return_inverse=True)
        Y = np.zeros((len(u_levels), len(t_levels)))
        filled = np.zeros(Y.shape, bool)
        Y[u_idx, t_idx] = y
        filled[u_idx, t_idx] = True
        if not filled.all():
            missing = np.argwhere(~filled)[:5]
            pairs = [(str(u_levels[i]), str(t_levels[j])) for i, j in missing]
            raise ValueError(
                f"unbalanced panel: {int((~filled).sum())} missing "
                f"(unit, time) cells, e.g. {pairs}; synthetic-control weights "
                f"require a complete outcome grid")
        treated_units = np.zeros(len(u_levels), bool)
        treated_units[u_idx[treat > 0]] = True
        post_times = np.zeros(len(t_levels), bool)
        post_times[t_idx[post > 0]] = True
        return Y, treated_units, post_times, u_levels, t_levels

    def _fit(self, df: DataFrame) -> DiffInDiffModel:
        self.require_columns(df, self.get("outcome_col"), self.get("treatment_col"),
                             self.get("post_treatment_col"), self.get("unit_col"),
                             self.get("time_col"))
        Y, treated, post, _, _ = self._panel(df)
        pre = ~post
        ctrl = Y[~treated]
        target = Y[treated].mean(axis=0)
        w, _ = constrained_least_squares(ctrl[:, pre].T, target[pre],
                                         ridge=self.get("ridge"))
        synth_post = w @ ctrl[:, post]
        effect = float(target[post].mean() - synth_post.mean())
        return DiffInDiffModel(treatment_effect=effect,
                               unit_weights=w.tolist())


class SyntheticDiffInDiffEstimator(SyntheticControlEstimator):
    """(ref ``SyntheticDiffInDiffEstimator.scala:28``)"""

    feature_name = "causal"

    def _fit(self, df: DataFrame) -> DiffInDiffModel:
        self.require_columns(df, self.get("outcome_col"), self.get("treatment_col"),
                             self.get("post_treatment_col"), self.get("unit_col"),
                             self.get("time_col"))
        Y, treated, post, _, _ = self._panel(df)
        pre = ~post
        ctrl, trt = Y[~treated], Y[treated]
        target = trt.mean(axis=0)
        # unit weights: match treated pre-trajectory with intercept (sdid)
        w_unit, _ = constrained_least_squares(ctrl[:, pre].T, target[pre],
                                              ridge=self.get("ridge"),
                                              fit_intercept=True)
        # time weights: pre-periods predicting the post-period average
        post_avg = ctrl[:, post].mean(axis=1)
        w_time, _ = constrained_least_squares(ctrl[:, pre], post_avg,
                                              ridge=self.get("ridge"),
                                              fit_intercept=True)
        # weighted DiD
        trt_post = target[post].mean()
        trt_pre = float(w_time @ target[pre])
        ctrl_post = float(w_unit @ ctrl[:, post].mean(axis=1))
        ctrl_pre = float(w_unit @ (ctrl[:, pre] @ w_time))
        effect = (trt_post - trt_pre) - (ctrl_post - ctrl_pre)
        return DiffInDiffModel(treatment_effect=float(effect),
                               unit_weights=w_unit.tolist(),
                               time_weights=w_time.tolist())
