"""Causal inference toolkit (reference ``core/.../causal/`` — SURVEY.md §2.5):
DoubleMLEstimator (cross-fitted ATE), OrthoForestDMLEstimator (heterogeneous
effects), the diff-in-diff family (DiffInDiffEstimator, SyntheticControl,
SyntheticDiffInDiff with simplex-constrained weight solvers — the reference's
``causal/opt/{MirrorDescent,ConstrainedLeastSquare}.scala``), and
ResidualTransformer."""

from .dml import DoubleMLEstimator, DoubleMLModel, OrthoForestDMLEstimator, OrthoForestDMLModel
from .did import DiffInDiffEstimator, SyntheticControlEstimator, SyntheticDiffInDiffEstimator
from .residual import ResidualTransformer
from .opt import constrained_least_squares, mirror_descent_simplex

__all__ = [
    "DoubleMLEstimator", "DoubleMLModel",
    "OrthoForestDMLEstimator", "OrthoForestDMLModel",
    "DiffInDiffEstimator", "SyntheticControlEstimator",
    "SyntheticDiffInDiffEstimator", "ResidualTransformer",
    "mirror_descent_simplex", "constrained_least_squares",
]
