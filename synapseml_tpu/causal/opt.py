"""Constrained optimizers for synthetic-control weights (reference
``causal/opt/MirrorDescent.scala`` / ``ConstrainedLeastSquare.scala``):
minimize |A w - b|^2 (+ ridge) subject to w on the probability simplex.

The reference solves this with entropic mirror descent; that converges slowly
on ill-conditioned panels, so the solver here is Nesterov-accelerated
projected gradient with an exact Euclidean simplex projection —
same constraint set, much faster convergence. ``mirror_descent_simplex``
keeps the reference-facing name.
"""

from __future__ import annotations

import numpy as np

__all__ = ["mirror_descent_simplex", "constrained_least_squares",
           "project_simplex"]


def project_simplex(v: np.ndarray) -> np.ndarray:
    """Euclidean projection onto {w >= 0, sum w = 1} (sort-based, O(k log k))."""
    u = np.sort(v)[::-1]
    css = np.cumsum(u)
    rho_candidates = u + (1.0 - css) / np.arange(1, len(v) + 1)
    rho = np.nonzero(rho_candidates > 0)[0][-1]
    theta = (css[rho] - 1.0) / (rho + 1)
    return np.maximum(v - theta, 0.0)


def mirror_descent_simplex(A: np.ndarray, b: np.ndarray, ridge: float = 0.0,
                           n_iter: int = 2000, lr: float | None = None,
                           tol: float = 1e-12) -> np.ndarray:
    """Simplex-constrained least squares: accelerated projected gradient."""
    n, k = A.shape
    AtA = A.T @ A / max(n, 1)
    Atb = A.T @ b / max(n, 1)
    # gradient is 2(AtA z - Atb) + 2 ridge z -> Lipschitz constant 2(λmax + ridge)
    L = 2.0 * (float(np.linalg.eigvalsh(AtA)[-1]) + ridge) + 1e-12
    step = 1.0 / L
    w = np.full(k, 1.0 / k)
    z = w.copy()
    t_acc = 1.0
    prev_loss = np.inf
    for _ in range(n_iter):
        grad = 2.0 * (AtA @ z - Atb) + 2.0 * ridge * z
        w_new = project_simplex(z - step * grad)
        t_new = 0.5 * (1.0 + np.sqrt(1.0 + 4.0 * t_acc * t_acc))
        z = w_new + ((t_acc - 1.0) / t_new) * (w_new - w)
        w, t_acc = w_new, t_new
        loss = float(w @ (AtA @ w) - 2.0 * (Atb @ w)) + ridge * float(w @ w)
        if abs(prev_loss - loss) < tol:
            break
        prev_loss = loss
    return w


def constrained_least_squares(A: np.ndarray, b: np.ndarray, ridge: float = 1e-6,
                              fit_intercept: bool = False,
                              n_iter: int = 2000) -> tuple[np.ndarray, float]:
    """Simplex-constrained least squares, optionally with a free intercept
    (the synthetic-DiD time-weight problem). Returns (weights, intercept)."""
    if not fit_intercept:
        return mirror_descent_simplex(A, b, ridge=ridge, n_iter=n_iter), 0.0
    # alternate: with w on the simplex the intercept is the weighted mean gap
    intercept = 0.0
    w = np.full(A.shape[1], 1.0 / A.shape[1])
    for _ in range(20):
        w = mirror_descent_simplex(A, b - intercept, ridge=ridge, n_iter=n_iter)
        new_intercept = float(np.mean(b - A @ w))
        if abs(new_intercept - intercept) < 1e-10:
            intercept = new_intercept
            break
        intercept = new_intercept
    return w, intercept
