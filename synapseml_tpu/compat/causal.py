"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class DiffInDiffEstimator(WrapperBase):
    """(ref ``DiffInDiffEstimator.scala``) (wraps ``synapseml_tpu.causal.did.DiffInDiffEstimator``)."""

    _target = 'synapseml_tpu.causal.did.DiffInDiffEstimator'

    def setOutcomeCol(self, value):
        return self._set('outcome_col', value)

    def getOutcomeCol(self):
        return self._get('outcome_col')

    def setPostTreatmentCol(self, value):
        return self._set('post_treatment_col', value)

    def getPostTreatmentCol(self):
        return self._get('post_treatment_col')

    def setTreatmentCol(self, value):
        return self._set('treatment_col', value)

    def getTreatmentCol(self):
        return self._get('treatment_col')


class DiffInDiffModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.causal.did.DiffInDiffModel``)."""

    _target = 'synapseml_tpu.causal.did.DiffInDiffModel'

    def setStandardError(self, value):
        return self._set('standard_error', value)

    def getStandardError(self):
        return self._get('standard_error')

    def setTimeWeights(self, value):
        return self._set('time_weights', value)

    def getTimeWeights(self):
        return self._get('time_weights')

    def setTreatmentEffect(self, value):
        return self._set('treatment_effect', value)

    def getTreatmentEffect(self):
        return self._get('treatment_effect')

    def setUnitWeights(self, value):
        return self._set('unit_weights', value)

    def getUnitWeights(self):
        return self._get('unit_weights')


class SyntheticControlEstimator(WrapperBase):
    """(ref ``SyntheticControlEstimator.scala``) — panel data: unit_col x (wraps ``synapseml_tpu.causal.did.SyntheticControlEstimator``)."""

    _target = 'synapseml_tpu.causal.did.SyntheticControlEstimator'

    def setOutcomeCol(self, value):
        return self._set('outcome_col', value)

    def getOutcomeCol(self):
        return self._get('outcome_col')

    def setPostTreatmentCol(self, value):
        return self._set('post_treatment_col', value)

    def getPostTreatmentCol(self):
        return self._get('post_treatment_col')

    def setRidge(self, value):
        return self._set('ridge', value)

    def getRidge(self):
        return self._get('ridge')

    def setTimeCol(self, value):
        return self._set('time_col', value)

    def getTimeCol(self):
        return self._get('time_col')

    def setTreatmentCol(self, value):
        return self._set('treatment_col', value)

    def getTreatmentCol(self):
        return self._get('treatment_col')

    def setUnitCol(self, value):
        return self._set('unit_col', value)

    def getUnitCol(self):
        return self._get('unit_col')


class SyntheticDiffInDiffEstimator(WrapperBase):
    """(ref ``SyntheticDiffInDiffEstimator.scala:28``) (wraps ``synapseml_tpu.causal.did.SyntheticDiffInDiffEstimator``)."""

    _target = 'synapseml_tpu.causal.did.SyntheticDiffInDiffEstimator'

    def setOutcomeCol(self, value):
        return self._set('outcome_col', value)

    def getOutcomeCol(self):
        return self._get('outcome_col')

    def setPostTreatmentCol(self, value):
        return self._set('post_treatment_col', value)

    def getPostTreatmentCol(self):
        return self._get('post_treatment_col')

    def setRidge(self, value):
        return self._set('ridge', value)

    def getRidge(self):
        return self._get('ridge')

    def setTimeCol(self, value):
        return self._set('time_col', value)

    def getTimeCol(self):
        return self._get('time_col')

    def setTreatmentCol(self, value):
        return self._set('treatment_col', value)

    def getTreatmentCol(self):
        return self._get('treatment_col')

    def setUnitCol(self, value):
        return self._set('unit_col', value)

    def getUnitCol(self):
        return self._get('unit_col')


class DoubleMLEstimator(WrapperBase):
    """(ref ``DoubleMLEstimator.scala:63``) (wraps ``synapseml_tpu.causal.dml.DoubleMLEstimator``)."""

    _target = 'synapseml_tpu.causal.dml.DoubleMLEstimator'

    def setConfidenceLevel(self, value):
        return self._set('confidence_level', value)

    def getConfidenceLevel(self):
        return self._get('confidence_level')

    def setMaxIter(self, value):
        return self._set('max_iter', value)

    def getMaxIter(self):
        return self._get('max_iter')

    def setOutcomeCol(self, value):
        return self._set('outcome_col', value)

    def getOutcomeCol(self):
        return self._get('outcome_col')

    def setOutcomeModel(self, value):
        return self._set('outcome_model', value)

    def getOutcomeModel(self):
        return self._get('outcome_model')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setTreatmentCol(self, value):
        return self._set('treatment_col', value)

    def getTreatmentCol(self):
        return self._get('treatment_col')

    def setTreatmentModel(self, value):
        return self._set('treatment_model', value)

    def getTreatmentModel(self):
        return self._get('treatment_model')


class DoubleMLModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.causal.dml.DoubleMLModel``)."""

    _target = 'synapseml_tpu.causal.dml.DoubleMLModel'

    def setAte(self, value):
        return self._set('ate', value)

    def getAte(self):
        return self._get('ate')

    def setCi(self, value):
        return self._set('ci', value)

    def getCi(self):
        return self._get('ci')

    def setRawEstimates(self, value):
        return self._set('raw_estimates', value)

    def getRawEstimates(self):
        return self._get('raw_estimates')


class OrthoForestDMLEstimator(WrapperBase):
    """(ref ``OrthoForestDMLEstimator.scala:31``) (wraps ``synapseml_tpu.causal.dml.OrthoForestDMLEstimator``)."""

    _target = 'synapseml_tpu.causal.dml.OrthoForestDMLEstimator'

    def setHeterogeneityCols(self, value):
        return self._set('heterogeneity_cols', value)

    def getHeterogeneityCols(self):
        return self._get('heterogeneity_cols')

    def setMaxDepth(self, value):
        return self._set('max_depth', value)

    def getMaxDepth(self):
        return self._get('max_depth')

    def setMinSamplesLeaf(self, value):
        return self._set('min_samples_leaf', value)

    def getMinSamplesLeaf(self):
        return self._get('min_samples_leaf')

    def setNumTrees(self, value):
        return self._set('num_trees', value)

    def getNumTrees(self):
        return self._get('num_trees')

    def setOutcomeCol(self, value):
        return self._set('outcome_col', value)

    def getOutcomeCol(self):
        return self._get('outcome_col')

    def setOutcomeModel(self, value):
        return self._set('outcome_model', value)

    def getOutcomeModel(self):
        return self._get('outcome_model')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setTreatmentCol(self, value):
        return self._set('treatment_col', value)

    def getTreatmentCol(self):
        return self._get('treatment_col')

    def setTreatmentModel(self, value):
        return self._set('treatment_model', value)

    def getTreatmentModel(self):
        return self._get('treatment_model')


class OrthoForestDMLModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.causal.dml.OrthoForestDMLModel``)."""

    _target = 'synapseml_tpu.causal.dml.OrthoForestDMLModel'

    def setHeterogeneityCols(self, value):
        return self._set('heterogeneity_cols', value)

    def getHeterogeneityCols(self):
        return self._get('heterogeneity_cols')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setTrees(self, value):
        return self._set('trees', value)

    def getTrees(self):
        return self._get('trees')


class ResidualTransformer(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.causal.residual.ResidualTransformer``)."""

    _target = 'synapseml_tpu.causal.residual.ResidualTransformer'

    def setClassIndex(self, value):
        return self._set('class_index', value)

    def getClassIndex(self):
        return self._get('class_index')

    def setObservedCol(self, value):
        return self._set('observed_col', value)

    def getObservedCol(self):
        return self._get('observed_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setPredictedCol(self, value):
        return self._set('predicted_col', value)

    def getPredictedCol(self):
        return self._get('predicted_col')

