"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class AggregateBalanceMeasure(WrapperBase):
    """(ref ``AggregateBalanceMeasure.scala``) — single row: inequality indices (wraps ``synapseml_tpu.exploratory.balance.AggregateBalanceMeasure``)."""

    _target = 'synapseml_tpu.exploratory.balance.AggregateBalanceMeasure'

    def setEpsilon(self, value):
        return self._set('epsilon', value)

    def getEpsilon(self):
        return self._get('epsilon')

    def setSensitiveCols(self, value):
        return self._set('sensitive_cols', value)

    def getSensitiveCols(self):
        return self._get('sensitive_cols')


class DistributionBalanceMeasure(WrapperBase):
    """(ref ``DistributionBalanceMeasure.scala``) — one row per feature: (wraps ``synapseml_tpu.exploratory.balance.DistributionBalanceMeasure``)."""

    _target = 'synapseml_tpu.exploratory.balance.DistributionBalanceMeasure'

    def setSensitiveCols(self, value):
        return self._set('sensitive_cols', value)

    def getSensitiveCols(self):
        return self._get('sensitive_cols')


class FeatureBalanceMeasure(WrapperBase):
    """(ref ``FeatureBalanceMeasure.scala:38``) — one row per (feature, (wraps ``synapseml_tpu.exploratory.balance.FeatureBalanceMeasure``)."""

    _target = 'synapseml_tpu.exploratory.balance.FeatureBalanceMeasure'

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setSensitiveCols(self, value):
        return self._set('sensitive_cols', value)

    def getSensitiveCols(self):
        return self._get('sensitive_cols')

