"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class AIFoundryChatCompletion(WrapperBase):
    """Subclasses define ``build_request(row_params) -> HTTPRequest`` and (wraps ``synapseml_tpu.services.aifoundry.AIFoundryChatCompletion``)."""

    _target = 'synapseml_tpu.services.aifoundry.AIFoundryChatCompletion'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setMaxTokens(self, value):
        return self._set('max_tokens', value)

    def getMaxTokens(self):
        return self._get('max_tokens')

    def setMessagesCol(self, value):
        return self._set('messages_col', value)

    def getMessagesCol(self):
        return self._get('messages_col')

    def setModel(self, value):
        return self._set('model', value)

    def getModel(self):
        return self._get('model')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTemperature(self, value):
        return self._set('temperature', value)

    def getTemperature(self):
        return self._get('temperature')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class DetectAnomalies(WrapperBase):
    """(ref ``DetectAnomalies``) — whole-series batch detection. (wraps ``synapseml_tpu.services.anomaly.DetectAnomalies``)."""

    _target = 'synapseml_tpu.services.anomaly.DetectAnomalies'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setGranularity(self, value):
        return self._set('granularity', value)

    def getGranularity(self):
        return self._get('granularity')

    def setMaxAnomalyRatio(self, value):
        return self._set('max_anomaly_ratio', value)

    def getMaxAnomalyRatio(self):
        return self._get('max_anomaly_ratio')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSensitivity(self, value):
        return self._set('sensitivity', value)

    def getSensitivity(self):
        return self._get('sensitivity')

    def setSeriesCol(self, value):
        return self._set('series_col', value)

    def getSeriesCol(self):
        return self._get('series_col')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class DetectLastAnomaly(WrapperBase):
    """(ref ``DetectLastAnomaly``) — is the latest point of the series anomalous. (wraps ``synapseml_tpu.services.anomaly.DetectLastAnomaly``)."""

    _target = 'synapseml_tpu.services.anomaly.DetectLastAnomaly'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setGranularity(self, value):
        return self._set('granularity', value)

    def getGranularity(self):
        return self._get('granularity')

    def setMaxAnomalyRatio(self, value):
        return self._set('max_anomaly_ratio', value)

    def getMaxAnomalyRatio(self):
        return self._get('max_anomaly_ratio')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSensitivity(self, value):
        return self._set('sensitivity', value)

    def getSensitivity(self):
        return self._get('sensitivity')

    def setSeriesCol(self, value):
        return self._set('series_col', value)

    def getSeriesCol(self):
        return self._get('series_col')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class DetectMultivariateAnomaly(WrapperBase):
    """Inference side: POST detect job for a window, poll the result. (wraps ``synapseml_tpu.services.anomaly.DetectMultivariateAnomaly``)."""

    _target = 'synapseml_tpu.services.anomaly.DetectMultivariateAnomaly'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setEndTimeCol(self, value):
        return self._set('end_time_col', value)

    def getEndTimeCol(self):
        return self._get('end_time_col')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setLroDeadlineS(self, value):
        return self._set('lro_deadline_s', value)

    def getLroDeadlineS(self):
        return self._get('lro_deadline_s')

    def setMaxPollAttempts(self, value):
        return self._set('max_poll_attempts', value)

    def getMaxPollAttempts(self):
        return self._get('max_poll_attempts')

    def setModelId(self, value):
        return self._set('model_id', value)

    def getModelId(self):
        return self._get('model_id')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setPollingIntervalS(self, value):
        return self._set('polling_interval_s', value)

    def getPollingIntervalS(self):
        return self._get('polling_interval_s')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSourceCol(self, value):
        return self._set('source_col', value)

    def getSourceCol(self):
        return self._get('source_col')

    def setStartTimeCol(self, value):
        return self._set('start_time_col', value)

    def getStartTimeCol(self):
        return self._get('start_time_col')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class FitMultivariateAnomaly(WrapperBase):
    """(ref ``MultivariateAnomalyDetection.scala:184-269`` FitMultivariate- (wraps ``synapseml_tpu.services.anomaly.FitMultivariateAnomaly``)."""

    _target = 'synapseml_tpu.services.anomaly.FitMultivariateAnomaly'

    def setAlignMode(self, value):
        return self._set('align_mode', value)

    def getAlignMode(self):
        return self._get('align_mode')

    def setEndTime(self, value):
        return self._set('end_time', value)

    def getEndTime(self):
        return self._get('end_time')

    def setFillNaMethod(self, value):
        return self._set('fill_na_method', value)

    def getFillNaMethod(self):
        return self._get('fill_na_method')

    def setMaxPollAttempts(self, value):
        return self._set('max_poll_attempts', value)

    def getMaxPollAttempts(self):
        return self._get('max_poll_attempts')

    def setPollingIntervalS(self, value):
        return self._set('polling_interval_s', value)

    def getPollingIntervalS(self):
        return self._get('polling_interval_s')

    def setSlidingWindow(self, value):
        return self._set('sliding_window', value)

    def getSlidingWindow(self):
        return self._get('sliding_window')

    def setSource(self, value):
        return self._set('source', value)

    def getSource(self):
        return self._get('source')

    def setStartTime(self, value):
        return self._set('start_time', value)

    def getStartTime(self):
        return self._get('start_time')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class SimpleDetectAnomalies(WrapperBase):
    """(ref ``SimpleDetectAnomalies``) — long-format rows (group, timestamp, (wraps ``synapseml_tpu.services.anomaly.SimpleDetectAnomalies``)."""

    _target = 'synapseml_tpu.services.anomaly.SimpleDetectAnomalies'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setGranularity(self, value):
        return self._set('granularity', value)

    def getGranularity(self):
        return self._get('granularity')

    def setGroupCol(self, value):
        return self._set('group_col', value)

    def getGroupCol(self):
        return self._get('group_col')

    def setMaxAnomalyRatio(self, value):
        return self._set('max_anomaly_ratio', value)

    def getMaxAnomalyRatio(self):
        return self._get('max_anomaly_ratio')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSensitivity(self, value):
        return self._set('sensitivity', value)

    def getSensitivity(self):
        return self._get('sensitivity')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setTimestampCol(self, value):
        return self._set('timestamp_col', value)

    def getTimestampCol(self):
        return self._get('timestamp_col')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')

    def setValueCol(self, value):
        return self._set('value_col', value)

    def getValueCol(self):
        return self._get('value_col')


class CognitiveServiceBase(WrapperBase):
    """Subclasses define ``build_request(row_params) -> HTTPRequest`` and (wraps ``synapseml_tpu.services.base.CognitiveServiceBase``)."""

    _target = 'synapseml_tpu.services.base.CognitiveServiceBase'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class HasAsyncReply(WrapperBase):
    """Long-running-operation support (reference ``HasAsyncReply`` / (wraps ``synapseml_tpu.services.base.HasAsyncReply``)."""

    _target = 'synapseml_tpu.services.base.HasAsyncReply'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setLroDeadlineS(self, value):
        return self._set('lro_deadline_s', value)

    def getLroDeadlineS(self):
        return self._get('lro_deadline_s')

    def setMaxPollAttempts(self, value):
        return self._set('max_poll_attempts', value)

    def getMaxPollAttempts(self):
        return self._get('max_poll_attempts')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setPollingIntervalS(self, value):
        return self._set('polling_interval_s', value)

    def getPollingIntervalS(self):
        return self._get('polling_interval_s')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class DetectFace(WrapperBase):
    """(ref ``DetectFace``) (wraps ``synapseml_tpu.services.face.DetectFace``)."""

    _target = 'synapseml_tpu.services.face.DetectFace'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setImageUrlCol(self, value):
        return self._set('image_url_col', value)

    def getImageUrlCol(self):
        return self._get('image_url_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setReturnFaceAttributes(self, value):
        return self._set('return_face_attributes', value)

    def getReturnFaceAttributes(self):
        return self._get('return_face_attributes')

    def setReturnFaceId(self, value):
        return self._set('return_face_id', value)

    def getReturnFaceId(self):
        return self._get('return_face_id')

    def setReturnFaceLandmarks(self, value):
        return self._set('return_face_landmarks', value)

    def getReturnFaceLandmarks(self):
        return self._get('return_face_landmarks')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class FindSimilarFace(WrapperBase):
    """(ref ``FindSimilar``) (wraps ``synapseml_tpu.services.face.FindSimilarFace``)."""

    _target = 'synapseml_tpu.services.face.FindSimilarFace'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setFaceIdCol(self, value):
        return self._set('face_id_col', value)

    def getFaceIdCol(self):
        return self._get('face_id_col')

    def setFaceIds(self, value):
        return self._set('face_ids', value)

    def getFaceIds(self):
        return self._get('face_ids')

    def setMaxCandidates(self, value):
        return self._set('max_candidates', value)

    def getMaxCandidates(self):
        return self._get('max_candidates')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class GroupFaces(WrapperBase):
    """(ref ``GroupFaces``) (wraps ``synapseml_tpu.services.face.GroupFaces``)."""

    _target = 'synapseml_tpu.services.face.GroupFaces'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setFaceIdsCol(self, value):
        return self._set('face_ids_col', value)

    def getFaceIdsCol(self):
        return self._get('face_ids_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class IdentifyFaces(WrapperBase):
    """(ref ``IdentifyFaces``) (wraps ``synapseml_tpu.services.face.IdentifyFaces``)."""

    _target = 'synapseml_tpu.services.face.IdentifyFaces'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setConfidenceThreshold(self, value):
        return self._set('confidence_threshold', value)

    def getConfidenceThreshold(self):
        return self._get('confidence_threshold')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setFaceIdsCol(self, value):
        return self._set('face_ids_col', value)

    def getFaceIdsCol(self):
        return self._get('face_ids_col')

    def setMaxCandidates(self, value):
        return self._set('max_candidates', value)

    def getMaxCandidates(self):
        return self._get('max_candidates')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setPersonGroupId(self, value):
        return self._set('person_group_id', value)

    def getPersonGroupId(self):
        return self._get('person_group_id')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class VerifyFaces(WrapperBase):
    """(ref ``VerifyFaces``) — same-person check for two face ids. (wraps ``synapseml_tpu.services.face.VerifyFaces``)."""

    _target = 'synapseml_tpu.services.face.VerifyFaces'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setFaceId1Col(self, value):
        return self._set('face_id1_col', value)

    def getFaceId1Col(self):
        return self._get('face_id1_col')

    def setFaceId2Col(self, value):
        return self._set('face_id2_col', value)

    def getFaceId2Col(self):
        return self._get('face_id2_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class AnalyzeBusinessCards(WrapperBase):
    """(ref ``FormRecognizer.scala`` AnalyzeDocument) — POST a document (URL (wraps ``synapseml_tpu.services.form.AnalyzeBusinessCards``)."""

    _target = 'synapseml_tpu.services.form.AnalyzeBusinessCards'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setImageBytesCol(self, value):
        return self._set('image_bytes_col', value)

    def getImageBytesCol(self):
        return self._get('image_bytes_col')

    def setImageUrlCol(self, value):
        return self._set('image_url_col', value)

    def getImageUrlCol(self):
        return self._get('image_url_col')

    def setLocale(self, value):
        return self._set('locale', value)

    def getLocale(self):
        return self._get('locale')

    def setLroDeadlineS(self, value):
        return self._set('lro_deadline_s', value)

    def getLroDeadlineS(self):
        return self._get('lro_deadline_s')

    def setMaxPollAttempts(self, value):
        return self._set('max_poll_attempts', value)

    def getMaxPollAttempts(self):
        return self._get('max_poll_attempts')

    def setModelId(self, value):
        return self._set('model_id', value)

    def getModelId(self):
        return self._get('model_id')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setPages(self, value):
        return self._set('pages', value)

    def getPages(self):
        return self._get('pages')

    def setPollingIntervalS(self, value):
        return self._set('polling_interval_s', value)

    def getPollingIntervalS(self):
        return self._get('polling_interval_s')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class AnalyzeDocument(WrapperBase):
    """(ref ``FormRecognizer.scala`` AnalyzeDocument) — POST a document (URL (wraps ``synapseml_tpu.services.form.AnalyzeDocument``)."""

    _target = 'synapseml_tpu.services.form.AnalyzeDocument'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setImageBytesCol(self, value):
        return self._set('image_bytes_col', value)

    def getImageBytesCol(self):
        return self._get('image_bytes_col')

    def setImageUrlCol(self, value):
        return self._set('image_url_col', value)

    def getImageUrlCol(self):
        return self._get('image_url_col')

    def setLocale(self, value):
        return self._set('locale', value)

    def getLocale(self):
        return self._get('locale')

    def setLroDeadlineS(self, value):
        return self._set('lro_deadline_s', value)

    def getLroDeadlineS(self):
        return self._get('lro_deadline_s')

    def setMaxPollAttempts(self, value):
        return self._set('max_poll_attempts', value)

    def getMaxPollAttempts(self):
        return self._get('max_poll_attempts')

    def setModelId(self, value):
        return self._set('model_id', value)

    def getModelId(self):
        return self._get('model_id')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setPages(self, value):
        return self._set('pages', value)

    def getPages(self):
        return self._get('pages')

    def setPollingIntervalS(self, value):
        return self._set('polling_interval_s', value)

    def getPollingIntervalS(self):
        return self._get('polling_interval_s')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class AnalyzeIDDocuments(WrapperBase):
    """(ref ``FormRecognizer.scala`` AnalyzeDocument) — POST a document (URL (wraps ``synapseml_tpu.services.form.AnalyzeIDDocuments``)."""

    _target = 'synapseml_tpu.services.form.AnalyzeIDDocuments'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setImageBytesCol(self, value):
        return self._set('image_bytes_col', value)

    def getImageBytesCol(self):
        return self._get('image_bytes_col')

    def setImageUrlCol(self, value):
        return self._set('image_url_col', value)

    def getImageUrlCol(self):
        return self._get('image_url_col')

    def setLocale(self, value):
        return self._set('locale', value)

    def getLocale(self):
        return self._get('locale')

    def setLroDeadlineS(self, value):
        return self._set('lro_deadline_s', value)

    def getLroDeadlineS(self):
        return self._get('lro_deadline_s')

    def setMaxPollAttempts(self, value):
        return self._set('max_poll_attempts', value)

    def getMaxPollAttempts(self):
        return self._get('max_poll_attempts')

    def setModelId(self, value):
        return self._set('model_id', value)

    def getModelId(self):
        return self._get('model_id')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setPages(self, value):
        return self._set('pages', value)

    def getPages(self):
        return self._get('pages')

    def setPollingIntervalS(self, value):
        return self._set('polling_interval_s', value)

    def getPollingIntervalS(self):
        return self._get('polling_interval_s')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class AnalyzeInvoices(WrapperBase):
    """(ref ``FormRecognizer.scala`` AnalyzeDocument) — POST a document (URL (wraps ``synapseml_tpu.services.form.AnalyzeInvoices``)."""

    _target = 'synapseml_tpu.services.form.AnalyzeInvoices'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setImageBytesCol(self, value):
        return self._set('image_bytes_col', value)

    def getImageBytesCol(self):
        return self._get('image_bytes_col')

    def setImageUrlCol(self, value):
        return self._set('image_url_col', value)

    def getImageUrlCol(self):
        return self._get('image_url_col')

    def setLocale(self, value):
        return self._set('locale', value)

    def getLocale(self):
        return self._get('locale')

    def setLroDeadlineS(self, value):
        return self._set('lro_deadline_s', value)

    def getLroDeadlineS(self):
        return self._get('lro_deadline_s')

    def setMaxPollAttempts(self, value):
        return self._set('max_poll_attempts', value)

    def getMaxPollAttempts(self):
        return self._get('max_poll_attempts')

    def setModelId(self, value):
        return self._set('model_id', value)

    def getModelId(self):
        return self._get('model_id')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setPages(self, value):
        return self._set('pages', value)

    def getPages(self):
        return self._get('pages')

    def setPollingIntervalS(self, value):
        return self._set('polling_interval_s', value)

    def getPollingIntervalS(self):
        return self._get('polling_interval_s')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class AnalyzeLayout(WrapperBase):
    """(ref ``FormRecognizer.scala`` AnalyzeDocument) — POST a document (URL (wraps ``synapseml_tpu.services.form.AnalyzeLayout``)."""

    _target = 'synapseml_tpu.services.form.AnalyzeLayout'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setImageBytesCol(self, value):
        return self._set('image_bytes_col', value)

    def getImageBytesCol(self):
        return self._get('image_bytes_col')

    def setImageUrlCol(self, value):
        return self._set('image_url_col', value)

    def getImageUrlCol(self):
        return self._get('image_url_col')

    def setLocale(self, value):
        return self._set('locale', value)

    def getLocale(self):
        return self._get('locale')

    def setLroDeadlineS(self, value):
        return self._set('lro_deadline_s', value)

    def getLroDeadlineS(self):
        return self._get('lro_deadline_s')

    def setMaxPollAttempts(self, value):
        return self._set('max_poll_attempts', value)

    def getMaxPollAttempts(self):
        return self._get('max_poll_attempts')

    def setModelId(self, value):
        return self._set('model_id', value)

    def getModelId(self):
        return self._get('model_id')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setPages(self, value):
        return self._set('pages', value)

    def getPages(self):
        return self._get('pages')

    def setPollingIntervalS(self, value):
        return self._set('polling_interval_s', value)

    def getPollingIntervalS(self):
        return self._get('polling_interval_s')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class AnalyzeReceipts(WrapperBase):
    """(ref ``FormRecognizer.scala`` AnalyzeDocument) — POST a document (URL (wraps ``synapseml_tpu.services.form.AnalyzeReceipts``)."""

    _target = 'synapseml_tpu.services.form.AnalyzeReceipts'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setImageBytesCol(self, value):
        return self._set('image_bytes_col', value)

    def getImageBytesCol(self):
        return self._get('image_bytes_col')

    def setImageUrlCol(self, value):
        return self._set('image_url_col', value)

    def getImageUrlCol(self):
        return self._get('image_url_col')

    def setLocale(self, value):
        return self._set('locale', value)

    def getLocale(self):
        return self._get('locale')

    def setLroDeadlineS(self, value):
        return self._set('lro_deadline_s', value)

    def getLroDeadlineS(self):
        return self._get('lro_deadline_s')

    def setMaxPollAttempts(self, value):
        return self._set('max_poll_attempts', value)

    def getMaxPollAttempts(self):
        return self._get('max_poll_attempts')

    def setModelId(self, value):
        return self._set('model_id', value)

    def getModelId(self):
        return self._get('model_id')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setPages(self, value):
        return self._set('pages', value)

    def getPages(self):
        return self._get('pages')

    def setPollingIntervalS(self, value):
        return self._set('polling_interval_s', value)

    def getPollingIntervalS(self):
        return self._get('polling_interval_s')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class FormOntologyLearner(WrapperBase):
    """(ref ``FormOntologyLearner.scala``) — unions the field schemas seen in (wraps ``synapseml_tpu.services.form.FormOntologyLearner``)."""

    _target = 'synapseml_tpu.services.form.FormOntologyLearner'

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setMinFrequency(self, value):
        return self._set('min_frequency', value)

    def getMinFrequency(self):
        return self._get('min_frequency')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')


class FormOntologyTransformer(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.services.form.FormOntologyTransformer``)."""

    _target = 'synapseml_tpu.services.form.FormOntologyTransformer'

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setOntology(self, value):
        return self._set('ontology', value)

    def getOntology(self):
        return self._get('ontology')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')


class AddressGeocoder(WrapperBase):
    """(ref ``AzureMapsGeocode``) — address string -> lat/lon candidates. (wraps ``synapseml_tpu.services.geospatial.AddressGeocoder``)."""

    _target = 'synapseml_tpu.services.geospatial.AddressGeocoder'

    def setAddressCol(self, value):
        return self._set('address_col', value)

    def getAddressCol(self):
        return self._get('address_col')

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setLimit(self, value):
        return self._set('limit', value)

    def getLimit(self):
        return self._get('limit')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class CheckPointInPolygon(WrapperBase):
    """(ref ``CheckPointInPolygon``) — is (lat, lon) inside a stored geofence (wraps ``synapseml_tpu.services.geospatial.CheckPointInPolygon``)."""

    _target = 'synapseml_tpu.services.geospatial.CheckPointInPolygon'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setLatCol(self, value):
        return self._set('lat_col', value)

    def getLatCol(self):
        return self._get('lat_col')

    def setLonCol(self, value):
        return self._set('lon_col', value)

    def getLonCol(self):
        return self._get('lon_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')

    def setUserDataId(self, value):
        return self._set('user_data_id', value)

    def getUserDataId(self):
        return self._get('user_data_id')


class ReverseAddressGeocoder(WrapperBase):
    """(ref reverse geocode) — (lat, lon) -> nearest address. (wraps ``synapseml_tpu.services.geospatial.ReverseAddressGeocoder``)."""

    _target = 'synapseml_tpu.services.geospatial.ReverseAddressGeocoder'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setLatCol(self, value):
        return self._set('lat_col', value)

    def getLatCol(self):
        return self._get('lat_col')

    def setLonCol(self, value):
        return self._set('lon_col', value)

    def getLonCol(self):
        return self._get('lon_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class LangChainTransformer(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.services.langchain.LangChainTransformer``)."""

    _target = 'synapseml_tpu.services.langchain.LangChainTransformer'

    def setChain(self, value):
        return self._set('chain', value)

    def getChain(self):
        return self._get('chain')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')


class OpenAIChatCompletion(WrapperBase):
    """(ref ``OpenAIChatCompletion.scala:98``) — messages col holds a list of (wraps ``synapseml_tpu.services.openai.OpenAIChatCompletion``)."""

    _target = 'synapseml_tpu.services.openai.OpenAIChatCompletion'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setDeploymentName(self, value):
        return self._set('deployment_name', value)

    def getDeploymentName(self):
        return self._get('deployment_name')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setMaxTokens(self, value):
        return self._set('max_tokens', value)

    def getMaxTokens(self):
        return self._get('max_tokens')

    def setMessagesCol(self, value):
        return self._set('messages_col', value)

    def getMessagesCol(self):
        return self._get('messages_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTemperature(self, value):
        return self._set('temperature', value)

    def getTemperature(self):
        return self._get('temperature')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class OpenAICompletion(WrapperBase):
    """(ref ``OpenAICompletion.scala``) (wraps ``synapseml_tpu.services.openai.OpenAICompletion``)."""

    _target = 'synapseml_tpu.services.openai.OpenAICompletion'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setDeploymentName(self, value):
        return self._set('deployment_name', value)

    def getDeploymentName(self):
        return self._get('deployment_name')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setMaxTokens(self, value):
        return self._set('max_tokens', value)

    def getMaxTokens(self):
        return self._get('max_tokens')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setPromptCol(self, value):
        return self._set('prompt_col', value)

    def getPromptCol(self):
        return self._get('prompt_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTemperature(self, value):
        return self._set('temperature', value)

    def getTemperature(self):
        return self._get('temperature')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class OpenAIEmbedding(WrapperBase):
    """(ref ``OpenAIEmbedding.scala:27``) — emits the embedding vector (wraps ``synapseml_tpu.services.openai.OpenAIEmbedding``)."""

    _target = 'synapseml_tpu.services.openai.OpenAIEmbedding'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setDeploymentName(self, value):
        return self._set('deployment_name', value)

    def getDeploymentName(self):
        return self._get('deployment_name')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setMaxTokens(self, value):
        return self._set('max_tokens', value)

    def getMaxTokens(self):
        return self._get('max_tokens')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTemperature(self, value):
        return self._set('temperature', value)

    def getTemperature(self):
        return self._get('temperature')

    def setTextCol(self, value):
        return self._set('text_col', value)

    def getTextCol(self):
        return self._get('text_col')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class OpenAIPrompt(WrapperBase):
    """(ref ``OpenAIPrompt.scala:40-767``) — prompt template interpolated from (wraps ``synapseml_tpu.services.openai.OpenAIPrompt``)."""

    _target = 'synapseml_tpu.services.openai.OpenAIPrompt'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setDeploymentName(self, value):
        return self._set('deployment_name', value)

    def getDeploymentName(self):
        return self._get('deployment_name')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setMaxTokens(self, value):
        return self._set('max_tokens', value)

    def getMaxTokens(self):
        return self._get('max_tokens')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setPostProcessing(self, value):
        return self._set('post_processing', value)

    def getPostProcessing(self):
        return self._get('post_processing')

    def setPostProcessingOptions(self, value):
        return self._set('post_processing_options', value)

    def getPostProcessingOptions(self):
        return self._get('post_processing_options')

    def setPromptTemplate(self, value):
        return self._set('prompt_template', value)

    def getPromptTemplate(self):
        return self._get('prompt_template')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setSystemPrompt(self, value):
        return self._set('system_prompt', value)

    def getSystemPrompt(self):
        return self._get('system_prompt')

    def setTemperature(self, value):
        return self._set('temperature', value)

    def getTemperature(self):
        return self._get('temperature')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class OpenAIResponses(WrapperBase):
    """(ref ``OpenAIResponses.scala``) — the /responses API: ``input`` is a (wraps ``synapseml_tpu.services.openai.OpenAIResponses``)."""

    _target = 'synapseml_tpu.services.openai.OpenAIResponses'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setDeploymentName(self, value):
        return self._set('deployment_name', value)

    def getDeploymentName(self):
        return self._get('deployment_name')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setMaxTokens(self, value):
        return self._set('max_tokens', value)

    def getMaxTokens(self):
        return self._get('max_tokens')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTemperature(self, value):
        return self._set('temperature', value)

    def getTemperature(self):
        return self._get('temperature')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class AzureSearchWriter(WrapperBase):
    """Subclasses define ``build_request(row_params) -> HTTPRequest`` and (wraps ``synapseml_tpu.services.search.AzureSearchWriter``)."""

    _target = 'synapseml_tpu.services.search.AzureSearchWriter'

    def setActionCol(self, value):
        return self._set('action_col', value)

    def getActionCol(self):
        return self._get('action_col')

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setBatchSize(self, value):
        return self._set('batch_size', value)

    def getBatchSize(self):
        return self._get('batch_size')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setCreateIndexIfNotExists(self, value):
        return self._set('create_index_if_not_exists', value)

    def getCreateIndexIfNotExists(self):
        return self._get('create_index_if_not_exists')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setIndexJson(self, value):
        return self._set('index_json', value)

    def getIndexJson(self):
        return self._get('index_json')

    def setIndexName(self, value):
        return self._set('index_name', value)

    def getIndexName(self):
        return self._get('index_name')

    def setKeyCol(self, value):
        return self._set('key_col', value)

    def getKeyCol(self):
        return self._get('key_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class ConversationTranscriber(WrapperBase):
    """Long-audio transcription with per-utterance speaker diarization. (wraps ``synapseml_tpu.services.speech.ConversationTranscriber``)."""

    _target = 'synapseml_tpu.services.speech.ConversationTranscriber'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setAudioUrlCol(self, value):
        return self._set('audio_url_col', value)

    def getAudioUrlCol(self):
        return self._get('audio_url_col')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setDisplayName(self, value):
        return self._set('display_name', value)

    def getDisplayName(self):
        return self._get('display_name')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setLanguage(self, value):
        return self._set('language', value)

    def getLanguage(self):
        return self._get('language')

    def setLroDeadlineS(self, value):
        return self._set('lro_deadline_s', value)

    def getLroDeadlineS(self):
        return self._get('lro_deadline_s')

    def setMaxPollAttempts(self, value):
        return self._set('max_poll_attempts', value)

    def getMaxPollAttempts(self):
        return self._get('max_poll_attempts')

    def setMaxSpeakers(self, value):
        return self._set('max_speakers', value)

    def getMaxSpeakers(self):
        return self._get('max_speakers')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setPollingIntervalS(self, value):
        return self._set('polling_interval_s', value)

    def getPollingIntervalS(self):
        return self._get('polling_interval_s')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class SpeechToText(WrapperBase):
    """Audio bytes -> recognition JSON (DisplayText, offsets). (wraps ``synapseml_tpu.services.speech.SpeechToText``)."""

    _target = 'synapseml_tpu.services.speech.SpeechToText'

    def setAudioCol(self, value):
        return self._set('audio_col', value)

    def getAudioCol(self):
        return self._get('audio_col')

    def setAudioFormat(self, value):
        return self._set('audio_format', value)

    def getAudioFormat(self):
        return self._get('audio_format')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setFormat(self, value):
        return self._set('format', value)

    def getFormat(self):
        return self._get('format')

    def setLanguage(self, value):
        return self._set('language', value)

    def getLanguage(self):
        return self._get('language')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setProfanity(self, value):
        return self._set('profanity', value)

    def getProfanity(self):
        return self._get('profanity')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class TextToSpeech(WrapperBase):
    """Text -> synthesized audio bytes (SSML POST). (wraps ``synapseml_tpu.services.speech.TextToSpeech``)."""

    _target = 'synapseml_tpu.services.speech.TextToSpeech'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setLanguage(self, value):
        return self._set('language', value)

    def getLanguage(self):
        return self._get('language')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setOutputFormat(self, value):
        return self._set('output_format', value)

    def getOutputFormat(self):
        return self._get('output_format')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTextCol(self, value):
        return self._set('text_col', value)

    def getTextCol(self):
        return self._get('text_col')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')

    def setVoice(self, value):
        return self._set('voice', value)

    def getVoice(self):
        return self._get('voice')


class AnalyzeText(WrapperBase):
    """(ref ``AnalyzeText.scala``) generic analyze-text task. (wraps ``synapseml_tpu.services.text.AnalyzeText``)."""

    _target = 'synapseml_tpu.services.text.AnalyzeText'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setKind(self, value):
        return self._set('kind', value)

    def getKind(self):
        return self._get('kind')

    def setLanguage(self, value):
        return self._set('language', value)

    def getLanguage(self):
        return self._get('language')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTextCol(self, value):
        return self._set('text_col', value)

    def getTextCol(self):
        return self._get('text_col')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class AnalyzeTextLRO(WrapperBase):
    """Long-running analyze-text jobs (reference (wraps ``synapseml_tpu.services.text.AnalyzeTextLRO``)."""

    _target = 'synapseml_tpu.services.text.AnalyzeTextLRO'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setKind(self, value):
        return self._set('kind', value)

    def getKind(self):
        return self._get('kind')

    def setLanguage(self, value):
        return self._set('language', value)

    def getLanguage(self):
        return self._get('language')

    def setLroDeadlineS(self, value):
        return self._set('lro_deadline_s', value)

    def getLroDeadlineS(self):
        return self._get('lro_deadline_s')

    def setMaxPollAttempts(self, value):
        return self._set('max_poll_attempts', value)

    def getMaxPollAttempts(self):
        return self._get('max_poll_attempts')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setPollingIntervalS(self, value):
        return self._set('polling_interval_s', value)

    def getPollingIntervalS(self):
        return self._get('polling_interval_s')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTaskParameters(self, value):
        return self._set('task_parameters', value)

    def getTaskParameters(self):
        return self._get('task_parameters')

    def setTextCol(self, value):
        return self._set('text_col', value)

    def getTextCol(self):
        return self._get('text_col')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class EntityRecognizer(WrapperBase):
    """(ref ``AnalyzeText.scala``) generic analyze-text task. (wraps ``synapseml_tpu.services.text.EntityRecognizer``)."""

    _target = 'synapseml_tpu.services.text.EntityRecognizer'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setKind(self, value):
        return self._set('kind', value)

    def getKind(self):
        return self._get('kind')

    def setLanguage(self, value):
        return self._set('language', value)

    def getLanguage(self):
        return self._get('language')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTextCol(self, value):
        return self._set('text_col', value)

    def getTextCol(self):
        return self._get('text_col')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class KeyPhraseExtractor(WrapperBase):
    """(ref ``AnalyzeText.scala``) generic analyze-text task. (wraps ``synapseml_tpu.services.text.KeyPhraseExtractor``)."""

    _target = 'synapseml_tpu.services.text.KeyPhraseExtractor'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setKind(self, value):
        return self._set('kind', value)

    def getKind(self):
        return self._get('kind')

    def setLanguage(self, value):
        return self._set('language', value)

    def getLanguage(self):
        return self._get('language')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTextCol(self, value):
        return self._set('text_col', value)

    def getTextCol(self):
        return self._get('text_col')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class LanguageDetector(WrapperBase):
    """(ref ``AnalyzeText.scala``) generic analyze-text task. (wraps ``synapseml_tpu.services.text.LanguageDetector``)."""

    _target = 'synapseml_tpu.services.text.LanguageDetector'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setKind(self, value):
        return self._set('kind', value)

    def getKind(self):
        return self._get('kind')

    def setLanguage(self, value):
        return self._set('language', value)

    def getLanguage(self):
        return self._get('language')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTextCol(self, value):
        return self._set('text_col', value)

    def getTextCol(self):
        return self._get('text_col')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class TextSentiment(WrapperBase):
    """(ref ``TextSentiment``) (wraps ``synapseml_tpu.services.text.TextSentiment``)."""

    _target = 'synapseml_tpu.services.text.TextSentiment'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setKind(self, value):
        return self._set('kind', value)

    def getKind(self):
        return self._get('kind')

    def setLanguage(self, value):
        return self._set('language', value)

    def getLanguage(self):
        return self._get('language')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTextCol(self, value):
        return self._set('text_col', value)

    def getTextCol(self):
        return self._get('text_col')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class BreakSentence(WrapperBase):
    """Sentence boundary lengths (reference ``BreakSentence``): (wraps ``synapseml_tpu.services.translate.BreakSentence``)."""

    _target = 'synapseml_tpu.services.translate.BreakSentence'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setLanguage(self, value):
        return self._set('language', value)

    def getLanguage(self):
        return self._get('language')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTextCol(self, value):
        return self._set('text_col', value)

    def getTextCol(self):
        return self._get('text_col')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class DictionaryExamples(WrapperBase):
    """Usage examples for a (text, translation) pair (reference (wraps ``synapseml_tpu.services.translate.DictionaryExamples``)."""

    _target = 'synapseml_tpu.services.translate.DictionaryExamples'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setFromLanguage(self, value):
        return self._set('from_language', value)

    def getFromLanguage(self):
        return self._get('from_language')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTextCol(self, value):
        return self._set('text_col', value)

    def getTextCol(self):
        return self._get('text_col')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setToLanguage(self, value):
        return self._set('to_language', value)

    def getToLanguage(self):
        return self._get('to_language')

    def setTranslationCol(self, value):
        return self._set('translation_col', value)

    def getTranslationCol(self):
        return self._get('translation_col')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class DictionaryLookup(WrapperBase):
    """Alternative translations for a word/phrase (reference (wraps ``synapseml_tpu.services.translate.DictionaryLookup``)."""

    _target = 'synapseml_tpu.services.translate.DictionaryLookup'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setFromLanguage(self, value):
        return self._set('from_language', value)

    def getFromLanguage(self):
        return self._get('from_language')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTextCol(self, value):
        return self._set('text_col', value)

    def getTextCol(self):
        return self._get('text_col')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setToLanguage(self, value):
        return self._set('to_language', value)

    def getToLanguage(self):
        return self._get('to_language')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class Translate(WrapperBase):
    """Subclasses define ``build_request(row_params) -> HTTPRequest`` and (wraps ``synapseml_tpu.services.translate.Translate``)."""

    _target = 'synapseml_tpu.services.translate.Translate'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setFromLanguage(self, value):
        return self._set('from_language', value)

    def getFromLanguage(self):
        return self._get('from_language')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTextCol(self, value):
        return self._set('text_col', value)

    def getTextCol(self):
        return self._get('text_col')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setToLanguage(self, value):
        return self._set('to_language', value)

    def getToLanguage(self):
        return self._get('to_language')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class Transliterate(WrapperBase):
    """Convert text between scripts (reference ``Transliterate``): (wraps ``synapseml_tpu.services.translate.Transliterate``)."""

    _target = 'synapseml_tpu.services.translate.Transliterate'

    def setApiVersion(self, value):
        return self._set('api_version', value)

    def getApiVersion(self):
        return self._get('api_version')

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setFromScript(self, value):
        return self._set('from_script', value)

    def getFromScript(self):
        return self._get('from_script')

    def setLanguage(self, value):
        return self._set('language', value)

    def getLanguage(self):
        return self._get('language')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTextCol(self, value):
        return self._set('text_col', value)

    def getTextCol(self):
        return self._get('text_col')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setToScript(self, value):
        return self._set('to_script', value)

    def getToScript(self):
        return self._get('to_script')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class AnalyzeImage(WrapperBase):
    """(ref ``AnalyzeImage``) (wraps ``synapseml_tpu.services.vision.AnalyzeImage``)."""

    _target = 'synapseml_tpu.services.vision.AnalyzeImage'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setDetails(self, value):
        return self._set('details', value)

    def getDetails(self):
        return self._get('details')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setImageBytesCol(self, value):
        return self._set('image_bytes_col', value)

    def getImageBytesCol(self):
        return self._get('image_bytes_col')

    def setImageUrlCol(self, value):
        return self._set('image_url_col', value)

    def getImageUrlCol(self):
        return self._get('image_url_col')

    def setLanguage(self, value):
        return self._set('language', value)

    def getLanguage(self):
        return self._get('language')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')

    def setVisualFeatures(self, value):
        return self._set('visual_features', value)

    def getVisualFeatures(self):
        return self._get('visual_features')


class DescribeImage(WrapperBase):
    """Shared image-url-or-bytes input handling (ref ``HasImageInput``). (wraps ``synapseml_tpu.services.vision.DescribeImage``)."""

    _target = 'synapseml_tpu.services.vision.DescribeImage'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setImageBytesCol(self, value):
        return self._set('image_bytes_col', value)

    def getImageBytesCol(self):
        return self._get('image_bytes_col')

    def setImageUrlCol(self, value):
        return self._set('image_url_col', value)

    def getImageUrlCol(self):
        return self._get('image_url_col')

    def setMaxCandidates(self, value):
        return self._set('max_candidates', value)

    def getMaxCandidates(self):
        return self._get('max_candidates')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class GenerateThumbnails(WrapperBase):
    """Shared image-url-or-bytes input handling (ref ``HasImageInput``). (wraps ``synapseml_tpu.services.vision.GenerateThumbnails``)."""

    _target = 'synapseml_tpu.services.vision.GenerateThumbnails'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setHeight(self, value):
        return self._set('height', value)

    def getHeight(self):
        return self._get('height')

    def setImageBytesCol(self, value):
        return self._set('image_bytes_col', value)

    def getImageBytesCol(self):
        return self._get('image_bytes_col')

    def setImageUrlCol(self, value):
        return self._set('image_url_col', value)

    def getImageUrlCol(self):
        return self._get('image_url_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSmartCropping(self, value):
        return self._set('smart_cropping', value)

    def getSmartCropping(self):
        return self._get('smart_cropping')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')

    def setWidth(self, value):
        return self._set('width', value)

    def getWidth(self):
        return self._get('width')


class OCR(WrapperBase):
    """(ref ``OCR``) — synchronous printed-text recognition. (wraps ``synapseml_tpu.services.vision.OCR``)."""

    _target = 'synapseml_tpu.services.vision.OCR'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setDetectOrientation(self, value):
        return self._set('detect_orientation', value)

    def getDetectOrientation(self):
        return self._get('detect_orientation')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setImageBytesCol(self, value):
        return self._set('image_bytes_col', value)

    def getImageBytesCol(self):
        return self._get('image_bytes_col')

    def setImageUrlCol(self, value):
        return self._set('image_url_col', value)

    def getImageUrlCol(self):
        return self._get('image_url_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class ReadImage(WrapperBase):
    """(ref ``ReadImage``) — the async Read API: 202 + Operation-Location. (wraps ``synapseml_tpu.services.vision.ReadImage``)."""

    _target = 'synapseml_tpu.services.vision.ReadImage'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setImageBytesCol(self, value):
        return self._set('image_bytes_col', value)

    def getImageBytesCol(self):
        return self._get('image_bytes_col')

    def setImageUrlCol(self, value):
        return self._set('image_url_col', value)

    def getImageUrlCol(self):
        return self._get('image_url_col')

    def setLroDeadlineS(self, value):
        return self._set('lro_deadline_s', value)

    def getLroDeadlineS(self):
        return self._get('lro_deadline_s')

    def setMaxPollAttempts(self, value):
        return self._set('max_poll_attempts', value)

    def getMaxPollAttempts(self):
        return self._get('max_poll_attempts')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setPollingIntervalS(self, value):
        return self._set('polling_interval_s', value)

    def getPollingIntervalS(self):
        return self._get('polling_interval_s')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class RecognizeDomainSpecificContent(WrapperBase):
    """Shared image-url-or-bytes input handling (ref ``HasImageInput``). (wraps ``synapseml_tpu.services.vision.RecognizeDomainSpecificContent``)."""

    _target = 'synapseml_tpu.services.vision.RecognizeDomainSpecificContent'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setImageBytesCol(self, value):
        return self._set('image_bytes_col', value)

    def getImageBytesCol(self):
        return self._get('image_bytes_col')

    def setImageUrlCol(self, value):
        return self._set('image_url_col', value)

    def getImageUrlCol(self):
        return self._get('image_url_col')

    def setModel(self, value):
        return self._set('model', value)

    def getModel(self):
        return self._get('model')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')


class TagImage(WrapperBase):
    """Shared image-url-or-bytes input handling (ref ``HasImageInput``). (wraps ``synapseml_tpu.services.vision.TagImage``)."""

    _target = 'synapseml_tpu.services.vision.TagImage'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setImageBytesCol(self, value):
        return self._set('image_bytes_col', value)

    def getImageBytesCol(self):
        return self._get('image_bytes_col')

    def setImageUrlCol(self, value):
        return self._set('image_url_col', value)

    def getImageUrlCol(self):
        return self._get('image_url_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setSubscriptionKey(self, value):
        return self._set('subscription_key', value)

    def getSubscriptionKey(self):
        return self._get('subscription_key')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

    def setUrl(self, value):
        return self._set('url', value)

    def getUrl(self):
        return self._get('url')

