"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class ComputeModelStatistics(WrapperBase):
    """(ref ``ComputeModelStatistics.scala:58``) — returns a one-row metrics (wraps ``synapseml_tpu.train.statistics.ComputeModelStatistics``)."""

    _target = 'synapseml_tpu.train.statistics.ComputeModelStatistics'

    def setEvaluationMetric(self, value):
        return self._set('evaluation_metric', value)

    def getEvaluationMetric(self):
        return self._get('evaluation_metric')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setScoredProbabilitiesCol(self, value):
        return self._set('scored_probabilities_col', value)

    def getScoredProbabilitiesCol(self):
        return self._get('scored_probabilities_col')

    def setScoresCol(self, value):
        return self._set('scores_col', value)

    def getScoresCol(self):
        return self._get('scores_col')


class ComputePerInstanceStatistics(WrapperBase):
    """Per-row loss/correctness (ref ``ComputePerInstanceStatistics.scala``). (wraps ``synapseml_tpu.train.statistics.ComputePerInstanceStatistics``)."""

    _target = 'synapseml_tpu.train.statistics.ComputePerInstanceStatistics'

    def setEvaluationMetric(self, value):
        return self._set('evaluation_metric', value)

    def getEvaluationMetric(self):
        return self._get('evaluation_metric')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setScoredProbabilitiesCol(self, value):
        return self._set('scored_probabilities_col', value)

    def getScoredProbabilitiesCol(self):
        return self._get('scored_probabilities_col')

    def setScoresCol(self, value):
        return self._set('scores_col', value)

    def getScoresCol(self):
        return self._get('scores_col')


class TrainClassifier(WrapperBase):
    """(ref ``TrainClassifier.scala:52``) (wraps ``synapseml_tpu.train.train.TrainClassifier``)."""

    _target = 'synapseml_tpu.train.train.TrainClassifier'

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setModel(self, value):
        return self._set('model', value)

    def getModel(self):
        return self._get('model')

    def setNumFeatures(self, value):
        return self._set('num_features', value)

    def getNumFeatures(self):
        return self._get('num_features')


class TrainRegressor(WrapperBase):
    """(ref ``train/TrainRegressor.scala``) (wraps ``synapseml_tpu.train.train.TrainRegressor``)."""

    _target = 'synapseml_tpu.train.train.TrainRegressor'

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setModel(self, value):
        return self._set('model', value)

    def getModel(self):
        return self._get('model')

    def setNumFeatures(self, value):
        return self._set('num_features', value)

    def getNumFeatures(self):
        return self._get('num_features')


class TrainedClassifierModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.train.train.TrainedClassifierModel``)."""

    _target = 'synapseml_tpu.train.train.TrainedClassifierModel'

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setFeaturizer(self, value):
        return self._set('featurizer', value)

    def getFeaturizer(self):
        return self._get('featurizer')

    def setInnerModel(self, value):
        return self._set('inner_model', value)

    def getInnerModel(self):
        return self._get('inner_model')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setLabelIndexer(self, value):
        return self._set('label_indexer', value)

    def getLabelIndexer(self):
        return self._get('label_indexer')


class TrainedRegressorModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.train.train.TrainedRegressorModel``)."""

    _target = 'synapseml_tpu.train.train.TrainedRegressorModel'

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setFeaturizer(self, value):
        return self._set('featurizer', value)

    def getFeaturizer(self):
        return self._get('featurizer')

    def setInnerModel(self, value):
        return self._set('inner_model', value)

    def getInnerModel(self):
        return self._get('inner_model')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

