"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class Cacher(WrapperBase):
    """(ref ``stages/Cacher.scala``) — the eager data plane is always (wraps ``synapseml_tpu.stages.basic.Cacher``)."""

    _target = 'synapseml_tpu.stages.basic.Cacher'

    def setDisable(self, value):
        return self._set('disable', value)

    def getDisable(self):
        return self._get('disable')


class ClassBalancer(WrapperBase):
    """Weight column = max_class_count / class_count (wraps ``synapseml_tpu.stages.basic.ClassBalancer``)."""

    _target = 'synapseml_tpu.stages.basic.ClassBalancer'

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')


class ClassBalancerModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.stages.basic.ClassBalancerModel``)."""

    _target = 'synapseml_tpu.stages.basic.ClassBalancerModel'

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setWeights(self, value):
        return self._set('weights', value)

    def getWeights(self):
        return self._get('weights')


class DropColumns(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.stages.basic.DropColumns``)."""

    _target = 'synapseml_tpu.stages.basic.DropColumns'

    def setCols(self, value):
        return self._set('cols', value)

    def getCols(self):
        return self._get('cols')


class EnsembleByKey(WrapperBase):
    """Group rows by key column(s) and aggregate value column(s) (wraps ``synapseml_tpu.stages.basic.EnsembleByKey``)."""

    _target = 'synapseml_tpu.stages.basic.EnsembleByKey'

    def setColNames(self, value):
        return self._set('col_names', value)

    def getColNames(self):
        return self._get('col_names')

    def setCollapseGroup(self, value):
        return self._set('collapse_group', value)

    def getCollapseGroup(self):
        return self._get('collapse_group')

    def setCols(self, value):
        return self._set('cols', value)

    def getCols(self):
        return self._get('cols')

    def setKeys(self, value):
        return self._set('keys', value)

    def getKeys(self):
        return self._get('keys')

    def setStrategy(self, value):
        return self._set('strategy', value)

    def getStrategy(self):
        return self._get('strategy')


class Explode(WrapperBase):
    """Explode an array column into rows (ref ``stages/Explode.scala``). (wraps ``synapseml_tpu.stages.basic.Explode``)."""

    _target = 'synapseml_tpu.stages.basic.Explode'

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')


class Lambda(WrapperBase):
    """Arbitrary DataFrame->DataFrame function as a stage (wraps ``synapseml_tpu.stages.basic.Lambda``)."""

    _target = 'synapseml_tpu.stages.basic.Lambda'

    def setTransformFn(self, value):
        return self._set('transform_fn', value)

    def getTransformFn(self):
        return self._get('transform_fn')

    def setTransformSchemaFn(self, value):
        return self._set('transform_schema_fn', value)

    def getTransformSchemaFn(self):
        return self._get('transform_schema_fn')


class MultiColumnAdapter(WrapperBase):
    """Apply a 1-col stage independently to many columns (wraps ``synapseml_tpu.stages.basic.MultiColumnAdapter``)."""

    _target = 'synapseml_tpu.stages.basic.MultiColumnAdapter'

    def setBaseStage(self, value):
        return self._set('base_stage', value)

    def getBaseStage(self):
        return self._get('base_stage')

    def setInputCols(self, value):
        return self._set('input_cols', value)

    def getInputCols(self):
        return self._get('input_cols')

    def setOutputCols(self, value):
        return self._set('output_cols', value)

    def getOutputCols(self):
        return self._get('output_cols')


class PartitionConsolidator(WrapperBase):
    """Funnel data to one partition per host (ref (wraps ``synapseml_tpu.stages.basic.PartitionConsolidator``)."""

    _target = 'synapseml_tpu.stages.basic.PartitionConsolidator'

    def setNumHosts(self, value):
        return self._set('num_hosts', value)

    def getNumHosts(self):
        return self._get('num_hosts')


class RenameColumn(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.stages.basic.RenameColumn``)."""

    _target = 'synapseml_tpu.stages.basic.RenameColumn'

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')


class Repartition(WrapperBase):
    """(ref ``stages/Repartition.scala``) — partitions map 1:1 to host feeding (wraps ``synapseml_tpu.stages.basic.Repartition``)."""

    _target = 'synapseml_tpu.stages.basic.Repartition'

    def setDisable(self, value):
        return self._set('disable', value)

    def getDisable(self):
        return self._get('disable')

    def setN(self, value):
        return self._set('n', value)

    def getN(self):
        return self._get('n')


class SelectColumns(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.stages.basic.SelectColumns``)."""

    _target = 'synapseml_tpu.stages.basic.SelectColumns'

    def setCols(self, value):
        return self._set('cols', value)

    def getCols(self):
        return self._get('cols')


class StratifiedRepartition(WrapperBase):
    """Repartition so every partition sees every label value (wraps ``synapseml_tpu.stages.basic.StratifiedRepartition``)."""

    _target = 'synapseml_tpu.stages.basic.StratifiedRepartition'

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setMode(self, value):
        return self._set('mode', value)

    def getMode(self):
        return self._get('mode')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')


class Timer(WrapperBase):
    """Time a wrapped stage's fit/transform (ref ``stages/Timer.scala:56``). (wraps ``synapseml_tpu.stages.basic.Timer``)."""

    _target = 'synapseml_tpu.stages.basic.Timer'

    def setLogToScala(self, value):
        return self._set('log_to_scala', value)

    def getLogToScala(self):
        return self._get('log_to_scala')

    def setStage(self, value):
        return self._set('stage', value)

    def getStage(self):
        return self._get('stage')


class TimerModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.stages.basic.TimerModel``)."""

    _target = 'synapseml_tpu.stages.basic.TimerModel'

    def setLogToScala(self, value):
        return self._set('log_to_scala', value)

    def getLogToScala(self):
        return self._get('log_to_scala')

    def setStage(self, value):
        return self._set('stage', value)

    def getStage(self):
        return self._get('stage')


class UDFTransformer(WrapperBase):
    """Apply a user function to input column(s) producing an output column (wraps ``synapseml_tpu.stages.basic.UDFTransformer``)."""

    _target = 'synapseml_tpu.stages.basic.UDFTransformer'

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setInputCols(self, value):
        return self._set('input_cols', value)

    def getInputCols(self):
        return self._get('input_cols')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setUdf(self, value):
        return self._set('udf', value)

    def getUdf(self):
        return self._get('udf')

    def setVectorized(self, value):
        return self._set('vectorized', value)

    def getVectorized(self):
        return self._get('vectorized')


class DynamicMiniBatchTransformer(WrapperBase):
    """Batch whatever is available, capped (ref ``MiniBatchTransformer.scala:55``). (wraps ``synapseml_tpu.stages.minibatch.DynamicMiniBatchTransformer``)."""

    _target = 'synapseml_tpu.stages.minibatch.DynamicMiniBatchTransformer'

    def setMaxBatchSize(self, value):
        return self._set('max_batch_size', value)

    def getMaxBatchSize(self):
        return self._get('max_batch_size')


class FixedMiniBatchTransformer(WrapperBase):
    """Group rows into fixed-size batches (ref ``MiniBatchTransformer.scala:153``). (wraps ``synapseml_tpu.stages.minibatch.FixedMiniBatchTransformer``)."""

    _target = 'synapseml_tpu.stages.minibatch.FixedMiniBatchTransformer'

    def setBatchSize(self, value):
        return self._set('batch_size', value)

    def getBatchSize(self):
        return self._get('batch_size')

    def setBuffered(self, value):
        return self._set('buffered', value)

    def getBuffered(self):
        return self._get('buffered')

    def setMaxBufferSize(self, value):
        return self._set('max_buffer_size', value)

    def getMaxBufferSize(self):
        return self._get('max_buffer_size')


class FlattenBatch(WrapperBase):
    """Explode batched array-columns back into per-element rows (wraps ``synapseml_tpu.stages.minibatch.FlattenBatch``)."""

    _target = 'synapseml_tpu.stages.minibatch.FlattenBatch'


class TimeIntervalMiniBatchTransformer(WrapperBase):
    """Batch by wall-clock interval (ref ``MiniBatchTransformer.scala:79``). (wraps ``synapseml_tpu.stages.minibatch.TimeIntervalMiniBatchTransformer``)."""

    _target = 'synapseml_tpu.stages.minibatch.TimeIntervalMiniBatchTransformer'

    def setMaxBatchSize(self, value):
        return self._set('max_batch_size', value)

    def getMaxBatchSize(self):
        return self._get('max_batch_size')

    def setMillisToWait(self, value):
        return self._set('millis_to_wait', value)

    def getMillisToWait(self):
        return self._get('millis_to_wait')


class SummarizeData(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.stages.summarize.SummarizeData``)."""

    _target = 'synapseml_tpu.stages.summarize.SummarizeData'

    def setBasic(self, value):
        return self._set('basic', value)

    def getBasic(self):
        return self._get('basic')

    def setCounts(self, value):
        return self._set('counts', value)

    def getCounts(self):
        return self._get('counts')

    def setErrorThreshold(self, value):
        return self._set('error_threshold', value)

    def getErrorThreshold(self):
        return self._get('error_threshold')

    def setPercentiles(self, value):
        return self._set('percentiles', value)

    def getPercentiles(self):
        return self._get('percentiles')

    def setSample(self, value):
        return self._set('sample', value)

    def getSample(self):
        return self._get('sample')


class TextPreprocessor(WrapperBase):
    """Longest-match substring replacement over a map (the reference builds a (wraps ``synapseml_tpu.stages.text.TextPreprocessor``)."""

    _target = 'synapseml_tpu.stages.text.TextPreprocessor'

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setMap(self, value):
        return self._set('map', value)

    def getMap(self):
        return self._get('map')

    def setNormalizeCase(self, value):
        return self._set('normalize_case', value)

    def getNormalizeCase(self):
        return self._get('normalize_case')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')


class UnicodeNormalize(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.stages.text.UnicodeNormalize``)."""

    _target = 'synapseml_tpu.stages.text.UnicodeNormalize'

    def setForm(self, value):
        return self._set('form', value)

    def getForm(self):
        return self._get('form')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setLower(self, value):
        return self._set('lower', value)

    def getLower(self):
        return self._get('lower')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

