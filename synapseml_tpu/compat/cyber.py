"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class AccessAnomaly(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.cyber.anomaly.AccessAnomaly``)."""

    _target = 'synapseml_tpu.cyber.anomaly.AccessAnomaly'

    def setLikelihoodCol(self, value):
        return self._set('likelihood_col', value)

    def getLikelihoodCol(self):
        return self._get('likelihood_col')

    def setMaxIter(self, value):
        return self._set('max_iter', value)

    def getMaxIter(self):
        return self._get('max_iter')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRank(self, value):
        return self._set('rank', value)

    def getRank(self):
        return self._get('rank')

    def setReg(self, value):
        return self._set('reg', value)

    def getReg(self):
        return self._get('reg')

    def setResCol(self, value):
        return self._set('res_col', value)

    def getResCol(self):
        return self._get('res_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setTenantCol(self, value):
        return self._set('tenant_col', value)

    def getTenantCol(self):
        return self._get('tenant_col')

    def setUserCol(self, value):
        return self._set('user_col', value)

    def getUserCol(self):
        return self._get('user_col')


class AccessAnomalyModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.cyber.anomaly.AccessAnomalyModel``)."""

    _target = 'synapseml_tpu.cyber.anomaly.AccessAnomalyModel'

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setResCol(self, value):
        return self._set('res_col', value)

    def getResCol(self):
        return self._get('res_col')

    def setTenantCol(self, value):
        return self._set('tenant_col', value)

    def getTenantCol(self):
        return self._get('tenant_col')

    def setTenantModels(self, value):
        return self._set('tenant_models', value)

    def getTenantModels(self):
        return self._get('tenant_models')

    def setUserCol(self, value):
        return self._set('user_col', value)

    def getUserCol(self):
        return self._get('user_col')


class ComplementAccessTransformer(WrapperBase):
    """(ref ``cyber/anomaly/ComplementAccessTransformer``) — emit (user, res) (wraps ``synapseml_tpu.cyber.anomaly.ComplementAccessTransformer``)."""

    _target = 'synapseml_tpu.cyber.anomaly.ComplementAccessTransformer'

    def setFactor(self, value):
        return self._set('factor', value)

    def getFactor(self):
        return self._get('factor')

    def setResCol(self, value):
        return self._set('res_col', value)

    def getResCol(self):
        return self._get('res_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setTenantCol(self, value):
        return self._set('tenant_col', value)

    def getTenantCol(self):
        return self._get('tenant_col')

    def setUserCol(self, value):
        return self._set('user_col', value)

    def getUserCol(self):
        return self._get('user_col')


class IdIndexer(WrapperBase):
    """(ref ``cyber/feature/indexers.py``) per-tenant contiguous ids. (wraps ``synapseml_tpu.cyber.features.IdIndexer``)."""

    _target = 'synapseml_tpu.cyber.features.IdIndexer'

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setResetPerPartition(self, value):
        return self._set('reset_per_partition', value)

    def getResetPerPartition(self):
        return self._get('reset_per_partition')

    def setTenantCol(self, value):
        return self._set('tenant_col', value)

    def getTenantCol(self):
        return self._get('tenant_col')


class IdIndexerModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.cyber.features.IdIndexerModel``)."""

    _target = 'synapseml_tpu.cyber.features.IdIndexerModel'

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setMapping(self, value):
        return self._set('mapping', value)

    def getMapping(self):
        return self._get('mapping')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setTenantCol(self, value):
        return self._set('tenant_col', value)

    def getTenantCol(self):
        return self._get('tenant_col')


class PartitionedMinMaxScaler(WrapperBase):
    """(ref ``cyber/feature/scalers.py`` LinearScalarScaler) (wraps ``synapseml_tpu.cyber.features.PartitionedMinMaxScaler``)."""

    _target = 'synapseml_tpu.cyber.features.PartitionedMinMaxScaler'

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setMaxValue(self, value):
        return self._set('max_value', value)

    def getMaxValue(self):
        return self._get('max_value')

    def setMinValue(self, value):
        return self._set('min_value', value)

    def getMinValue(self):
        return self._get('min_value')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setTenantCol(self, value):
        return self._set('tenant_col', value)

    def getTenantCol(self):
        return self._get('tenant_col')


class PartitionedStandardScaler(WrapperBase):
    """(ref ``cyber/feature/scalers.py`` StandardScalarScaler) (wraps ``synapseml_tpu.cyber.features.PartitionedStandardScaler``)."""

    _target = 'synapseml_tpu.cyber.features.PartitionedStandardScaler'

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setTenantCol(self, value):
        return self._set('tenant_col', value)

    def getTenantCol(self):
        return self._get('tenant_col')

