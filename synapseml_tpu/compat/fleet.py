"""Generated passthrough namespace — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers).
Re-exports the public surface of ``synapseml_tpu.fleet`` so the compat layer covers
non-stage subsystems too (compat coverage is drift-tested).
"""


from synapseml_tpu.fleet import (  # noqa: F401
    AdmissionController,
    AdmissionDecision,
    AdmissionPolicy,
    FleetAutoscaler,
    FleetSignals,
    FleetSpec,
    ModelSLO,
    ResidencyManager,
    SubprocessWorkerLauncher,
    ThreadWorkerLauncher,
    TokenBucket,
    WorkerHandle,
    WorkerLauncher,
    artifact_nbytes,
    fleet_worker_main,
    model_from_path,
    model_path,
    priority_of,
    serve_multi_model,
)

__all__ = [
    'AdmissionController',
    'AdmissionDecision',
    'AdmissionPolicy',
    'FleetAutoscaler',
    'FleetSignals',
    'FleetSpec',
    'ModelSLO',
    'ResidencyManager',
    'SubprocessWorkerLauncher',
    'ThreadWorkerLauncher',
    'TokenBucket',
    'WorkerHandle',
    'WorkerLauncher',
    'artifact_nbytes',
    'fleet_worker_main',
    'model_from_path',
    'model_path',
    'priority_of',
    'serve_multi_model',
]
