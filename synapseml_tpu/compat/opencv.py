"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class ImageSetAugmenter(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.image.augment.ImageSetAugmenter``)."""

    _target = 'synapseml_tpu.image.augment.ImageSetAugmenter'

    def setFlipLeftRight(self, value):
        return self._set('flip_left_right', value)

    def getFlipLeftRight(self):
        return self._get('flip_left_right')

    def setFlipUpDown(self, value):
        return self._set('flip_up_down', value)

    def getFlipUpDown(self):
        return self._get('flip_up_down')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')


class SuperpixelTransformer(WrapperBase):
    """(ref ``SuperpixelTransformer.scala``) emits, per image, the superpixel (wraps ``synapseml_tpu.image.superpixel.SuperpixelTransformer``)."""

    _target = 'synapseml_tpu.image.superpixel.SuperpixelTransformer'

    def setCellSize(self, value):
        return self._set('cell_size', value)

    def getCellSize(self):
        return self._get('cell_size')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setModifier(self, value):
        return self._set('modifier', value)

    def getModifier(self):
        return self._get('modifier')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')


class ImageTransformer(WrapperBase):
    """Chain of image stages + normalization + optional tensor output (wraps ``synapseml_tpu.image.transforms.ImageTransformer``)."""

    _target = 'synapseml_tpu.image.transforms.ImageTransformer'

    def setColorScaleFactor(self, value):
        return self._set('color_scale_factor', value)

    def getColorScaleFactor(self):
        return self._get('color_scale_factor')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setNormMeans(self, value):
        return self._set('norm_means', value)

    def getNormMeans(self):
        return self._get('norm_means')

    def setNormStds(self, value):
        return self._set('norm_stds', value)

    def getNormStds(self):
        return self._get('norm_stds')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setStages(self, value):
        return self._set('stages', value)

    def getStages(self):
        return self._get('stages')

    def setToTensor(self, value):
        return self._set('to_tensor', value)

    def getToTensor(self):
        return self._get('to_tensor')


class UnrollBinaryImage(WrapperBase):
    """Decode ENCODED image bytes (png/jpeg) straight to the flat vector — (wraps ``synapseml_tpu.image.unroll.UnrollBinaryImage``)."""

    _target = 'synapseml_tpu.image.unroll.UnrollBinaryImage'

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')


class UnrollImage(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.image.unroll.UnrollImage``)."""

    _target = 'synapseml_tpu.image.unroll.UnrollImage'

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

