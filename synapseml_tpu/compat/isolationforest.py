"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class IsolationForest(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.isolationforest.iforest.IsolationForest``)."""

    _target = 'synapseml_tpu.isolationforest.iforest.IsolationForest'

    def setBootstrap(self, value):
        return self._set('bootstrap', value)

    def getBootstrap(self):
        return self._get('bootstrap')

    def setContamination(self, value):
        return self._set('contamination', value)

    def getContamination(self):
        return self._get('contamination')

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setMaxFeatures(self, value):
        return self._set('max_features', value)

    def getMaxFeatures(self):
        return self._get('max_features')

    def setMaxSamples(self, value):
        return self._set('max_samples', value)

    def getMaxSamples(self):
        return self._get('max_samples')

    def setNumEstimators(self, value):
        return self._set('num_estimators', value)

    def getNumEstimators(self):
        return self._get('num_estimators')

    def setPredictedLabelCol(self, value):
        return self._set('predicted_label_col', value)

    def getPredictedLabelCol(self):
        return self._get('predicted_label_col')

    def setRandomSeed(self, value):
        return self._set('random_seed', value)

    def getRandomSeed(self):
        return self._get('random_seed')

    def setScoreCol(self, value):
        return self._set('score_col', value)

    def getScoreCol(self):
        return self._get('score_col')


class IsolationForestModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.isolationforest.iforest.IsolationForestModel``)."""

    _target = 'synapseml_tpu.isolationforest.iforest.IsolationForestModel'

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setPredictedLabelCol(self, value):
        return self._set('predicted_label_col', value)

    def getPredictedLabelCol(self):
        return self._get('predicted_label_col')

    def setScoreCol(self, value):
        return self._set('score_col', value)

    def getScoreCol(self):
        return self._get('score_col')

    def setSubsampleSize(self, value):
        return self._set('subsample_size', value)

    def getSubsampleSize(self):
        return self._get('subsample_size')

    def setThreshold(self, value):
        return self._set('threshold', value)

    def getThreshold(self):
        return self._get('threshold')

    def setTrees(self, value):
        return self._set('trees', value)

    def getTrees(self):
        return self._get('trees')

