"""Generated passthrough namespace — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers).
Re-exports the public surface of ``synapseml_tpu.retrieval`` so the compat layer covers
non-stage subsystems too (compat coverage is drift-tested).
"""


from synapseml_tpu.retrieval import (  # noqa: F401
    HashEmbedder,
    INF,
    IndexShard,
    SHARD_MANIFEST,
    VectorIndexModel,
    build_index,
    compact_index,
    embed_corpus,
    extract_documents,
    index_model_for,
    ingest_deltas,
    list_shards,
    open_shard,
    publish_index,
    retrieval_metrics,
    retrieval_worker_main,
    score_batches,
    score_shard,
    shards_from_parts,
    write_shard,
)

__all__ = [
    'HashEmbedder',
    'INF',
    'IndexShard',
    'SHARD_MANIFEST',
    'VectorIndexModel',
    'build_index',
    'compact_index',
    'embed_corpus',
    'extract_documents',
    'index_model_for',
    'ingest_deltas',
    'list_shards',
    'open_shard',
    'publish_index',
    'retrieval_metrics',
    'retrieval_worker_main',
    'score_batches',
    'score_shard',
    'shards_from_parts',
    'write_shard',
]
