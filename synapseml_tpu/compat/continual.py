"""Generated passthrough namespace — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers).
Re-exports the public surface of ``synapseml_tpu.continual`` so the compat layer covers
non-stage subsystems too (compat coverage is drift-tested).
"""


from synapseml_tpu.continual import (  # noqa: F401
    ContinualLoop,
    ContinualSpec,
    LoopAborted,
    RequestLogger,
    TrainAttempt,
    TrainSupervisor,
    annotate_drift_gauge,
    drift_annotation,
    logged_request_source,
)

__all__ = [
    'ContinualLoop',
    'ContinualSpec',
    'LoopAborted',
    'RequestLogger',
    'TrainAttempt',
    'TrainSupervisor',
    'annotate_drift_gauge',
    'drift_annotation',
    'logged_request_source',
]
