"""Generated pyspark-style wrapper namespace — do not edit.

``synapseml_tpu.compat.<ns>`` mirrors the reference's
``synapse.ml.<ns>`` Python modules (camelCase setters/getters,
chaining). Regenerate with ``python -m synapseml_tpu.codegen``.
"""

import importlib

_MODULES = ['automl', 'causal', 'cntk', 'continual', 'core', 'cyber', 'dl', 'explainers', 'exploratory', 'featurize', 'fleet', 'hf', 'io', 'isolationforest', 'lightgbm', 'nn', 'onnx', 'opencv', 'rai', 'rai', 'recommendation', 'registry', 'retrieval', 'scoring', 'services', 'stages', 'train', 'vw']


_REGISTRY = None


def wrapper_for(stage_cls):
    """The generated wrapper class for a native stage class, or None."""
    global _REGISTRY
    if _REGISTRY is None:
        _REGISTRY = {}
        for ns in _MODULES:
            mod = importlib.import_module(f"{__name__}.{ns}")
            for name in dir(mod):
                obj = getattr(mod, name)
                if isinstance(obj, type) and getattr(obj, "_target", ""):
                    _REGISTRY[obj._target] = obj
    full = f"{stage_cls.__module__}.{stage_cls.__name__}"
    return _REGISTRY.get(full)
