"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class ConditionalKNN(WrapperBase):
    """(ref ``nn/ConditionalKNN.scala``) — neighbors restricted per query to (wraps ``synapseml_tpu.nn.knn.ConditionalKNN``)."""

    _target = 'synapseml_tpu.nn.knn.ConditionalKNN'

    def setConditionerCol(self, value):
        return self._set('conditioner_col', value)

    def getConditionerCol(self):
        return self._get('conditioner_col')

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setK(self, value):
        return self._set('k', value)

    def getK(self):
        return self._get('k')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setQueryBatch(self, value):
        return self._set('query_batch', value)

    def getQueryBatch(self):
        return self._get('query_batch')

    def setValuesCol(self, value):
        return self._set('values_col', value)

    def getValuesCol(self):
        return self._get('values_col')


class ConditionalKNNModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.nn.knn.ConditionalKNNModel``)."""

    _target = 'synapseml_tpu.nn.knn.ConditionalKNNModel'

    def setConditionerCol(self, value):
        return self._set('conditioner_col', value)

    def getConditionerCol(self):
        return self._get('conditioner_col')

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setIndex(self, value):
        return self._set('index', value)

    def getIndex(self):
        return self._get('index')

    def setK(self, value):
        return self._set('k', value)

    def getK(self):
        return self._get('k')

    def setLabels(self, value):
        return self._set('labels', value)

    def getLabels(self):
        return self._get('labels')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setQueryBatch(self, value):
        return self._set('query_batch', value)

    def getQueryBatch(self):
        return self._get('query_batch')

    def setValues(self, value):
        return self._set('values', value)

    def getValues(self):
        return self._get('values')


class KNN(WrapperBase):
    """(ref ``nn/KNN.scala:49``) (wraps ``synapseml_tpu.nn.knn.KNN``)."""

    _target = 'synapseml_tpu.nn.knn.KNN'

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setK(self, value):
        return self._set('k', value)

    def getK(self):
        return self._get('k')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setQueryBatch(self, value):
        return self._set('query_batch', value)

    def getQueryBatch(self):
        return self._get('query_batch')

    def setValuesCol(self, value):
        return self._set('values_col', value)

    def getValuesCol(self):
        return self._get('values_col')


class KNNModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.nn.knn.KNNModel``)."""

    _target = 'synapseml_tpu.nn.knn.KNNModel'

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setIndex(self, value):
        return self._set('index', value)

    def getIndex(self):
        return self._get('index')

    def setK(self, value):
        return self._set('k', value)

    def getK(self):
        return self._get('k')

    def setLabels(self, value):
        return self._set('labels', value)

    def getLabels(self):
        return self._get('labels')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setQueryBatch(self, value):
        return self._set('query_batch', value)

    def getQueryBatch(self):
        return self._get('query_batch')

    def setValues(self, value):
        return self._set('values', value)

    def getValues(self):
        return self._get('values')


class HashEmbedder(WrapperBase):
    """Deterministic feature-hashing text embedder (pure numpy, zero model (wraps ``synapseml_tpu.retrieval.build.HashEmbedder``)."""

    _target = 'synapseml_tpu.retrieval.build.HashEmbedder'

    def setDim(self, value):
        return self._set('dim', value)

    def getDim(self):
        return self._get('dim')

    def setNormalize(self, value):
        return self._set('normalize', value)

    def getNormalize(self):
        return self._get('normalize')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setTextCol(self, value):
        return self._set('text_col', value)

    def getTextCol(self):
        return self._get('text_col')


class VectorIndexModel(WrapperBase):
    """Top-k search over a roster of immutable :class:`IndexShard`s. (wraps ``synapseml_tpu.retrieval.model.VectorIndexModel``)."""

    _target = 'synapseml_tpu.retrieval.model.VectorIndexModel'

    def setDim(self, value):
        return self._set('dim', value)

    def getDim(self):
        return self._get('dim')

    def setIndexName(self, value):
        return self._set('index_name', value)

    def getIndexName(self):
        return self._get('index_name')

    def setInlineShards(self, value):
        return self._set('inline_shards', value)

    def getInlineShards(self):
        return self._get('inline_shards')

    def setK(self, value):
        return self._set('k', value)

    def getK(self):
        return self._get('k')

    def setMetric(self, value):
        return self._set('metric', value)

    def getMetric(self):
        return self._get('metric')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setQueryBatch(self, value):
        return self._set('query_batch', value)

    def getQueryBatch(self):
        return self._get('query_batch')

    def setShardNames(self, value):
        return self._set('shard_names', value)

    def getShardNames(self):
        return self._get('shard_names')

