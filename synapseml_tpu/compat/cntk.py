"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class CNTKModel(WrapperBase):
    """(ref ``cntk/CNTKModel.py``; scoring semantics of ``_CNTKModel``) (wraps ``synapseml_tpu.models.cntk.CNTKModel``)."""

    _target = 'synapseml_tpu.models.cntk.CNTKModel'

    def setArgmaxDict(self, value):
        return self._set('argmax_dict', value)

    def getArgmaxDict(self):
        return self._get('argmax_dict')

    def setFeedDict(self, value):
        return self._set('feed_dict', value)

    def getFeedDict(self):
        return self._get('feed_dict')

    def setFetchDict(self, value):
        return self._set('fetch_dict', value)

    def getFetchDict(self):
        return self._get('fetch_dict')

    def setMiniBatchSize(self, value):
        return self._set('mini_batch_size', value)

    def getMiniBatchSize(self):
        return self._get('mini_batch_size')

    def setModelPayload(self, value):
        return self._set('model_payload', value)

    def getModelPayload(self):
        return self._get('model_payload')

    def setSoftmaxDict(self, value):
        return self._set('softmax_dict', value)

    def getSoftmaxDict(self):
        return self._get('softmax_dict')

