"""Generated passthrough namespace — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers).
Re-exports the public surface of ``synapseml_tpu.registry`` so the compat layer covers
non-stage subsystems too (compat coverage is drift-tested).
"""


from synapseml_tpu.registry import (  # noqa: F401
    AOTCapture,
    AOTExecutableSet,
    ArtifactStore,
    CanaryController,
    Deployment,
    IntegrityError,
    ModelRegistry,
    PublishedVersion,
    RegistryReadOnlyError,
    ResolvedModel,
    admin_load,
    aot_mechanism,
    apply_autotune,
    atomic_write_bytes,
    autotune_stage,
    param_schema_hash,
    runtime_fingerprint,
    sha256_file,
    write_stream_verified,
)

__all__ = [
    'AOTCapture',
    'AOTExecutableSet',
    'ArtifactStore',
    'CanaryController',
    'Deployment',
    'IntegrityError',
    'ModelRegistry',
    'PublishedVersion',
    'RegistryReadOnlyError',
    'ResolvedModel',
    'admin_load',
    'aot_mechanism',
    'apply_autotune',
    'atomic_write_bytes',
    'autotune_stage',
    'param_schema_hash',
    'runtime_fingerprint',
    'sha256_file',
    'write_stream_verified',
]
