"""Runtime base for the generated pyspark-style wrappers (see
``synapseml_tpu.codegen.emit_wrappers``; reference
``core/.../codegen/Wrappable.scala:56-389`` emits the analogous Python
wrapper classes over Scala stages).

A wrapper owns a real stage instance and exposes the reference's surface
style: camelCase ``setX(value) -> self`` / ``getX()`` accessors, chaining
construction, and ``fit``/``transform`` that accept and return the same
DataFrames as the wrapped stage (``fit`` re-wraps the produced model when a
generated wrapper exists for it).
"""

from __future__ import annotations

import importlib

__all__ = ["WrapperBase"]


def _load(path: str):
    mod, name = path.rsplit(".", 1)
    return getattr(importlib.import_module(mod), name)


def _wrap_result(obj):
    """Wrap a produced stage (e.g. fit's model) when a wrapper is registered."""
    from . import wrapper_for

    cls = wrapper_for(type(obj))
    return cls(_wrapped=obj) if cls is not None else obj


class WrapperBase:
    """Generated subclasses set ``_target`` (full path of the wrapped stage
    class) and define camelCase accessors calling ``_set``/``_get``."""

    _target: str = ""

    def __init__(self, _wrapped=None, **kwargs):
        self._stage = _wrapped if _wrapped is not None else _load(self._target)()
        for k, v in kwargs.items():
            self._set(_snake(k), v)

    # ---- pyspark-style surface ----
    def _set(self, name: str, value):
        self._stage.set(**{name: value})
        return self

    def _get(self, name: str):
        return self._stage.get(name)

    def fit(self, df):
        return _wrap_result(self._stage.fit(df))

    def transform(self, df):
        return self._stage.transform(df)

    def save(self, path: str):
        self._stage.save(path)
        return self

    def unwrap(self):
        """The underlying native stage."""
        return self._stage

    def __repr__(self):
        return f"{type(self).__name__}({self._stage!r})"


def _snake(name: str) -> str:
    """setNumIterations/getNumIterations-style camelCase -> num_iterations."""
    out = []
    for ch in name:
        if ch.isupper():
            out.append("_")
            out.append(ch.lower())
        else:
            out.append(ch)
    s = "".join(out)
    return s[1:] if s.startswith("_") else s
