"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class RankingAdapter(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.recommendation.adapter.RankingAdapter``)."""

    _target = 'synapseml_tpu.recommendation.adapter.RankingAdapter'

    def setItemCol(self, value):
        return self._set('item_col', value)

    def getItemCol(self):
        return self._get('item_col')

    def setK(self, value):
        return self._set('k', value)

    def getK(self):
        return self._get('k')

    def setRecommender(self, value):
        return self._set('recommender', value)

    def getRecommender(self):
        return self._get('recommender')

    def setUserCol(self, value):
        return self._set('user_col', value)

    def getUserCol(self):
        return self._get('user_col')


class RankingAdapterModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.recommendation.adapter.RankingAdapterModel``)."""

    _target = 'synapseml_tpu.recommendation.adapter.RankingAdapterModel'

    def setItemCol(self, value):
        return self._set('item_col', value)

    def getItemCol(self):
        return self._get('item_col')

    def setK(self, value):
        return self._set('k', value)

    def getK(self):
        return self._get('k')

    def setRecommenderModel(self, value):
        return self._set('recommender_model', value)

    def getRecommenderModel(self):
        return self._get('recommender_model')

    def setUserCol(self, value):
        return self._set('user_col', value)

    def getUserCol(self):
        return self._get('user_col')


class RankingTrainValidationSplit(WrapperBase):
    """(ref ``RankingTrainValidationSplit.scala:25``) — per-user holdout split + (wraps ``synapseml_tpu.recommendation.adapter.RankingTrainValidationSplit``)."""

    _target = 'synapseml_tpu.recommendation.adapter.RankingTrainValidationSplit'

    def setEstimator(self, value):
        return self._set('estimator', value)

    def getEstimator(self):
        return self._get('estimator')

    def setEstimatorParamMaps(self, value):
        return self._set('estimator_param_maps', value)

    def getEstimatorParamMaps(self):
        return self._get('estimator_param_maps')

    def setEvaluator(self, value):
        return self._set('evaluator', value)

    def getEvaluator(self):
        return self._get('evaluator')

    def setItemCol(self, value):
        return self._set('item_col', value)

    def getItemCol(self):
        return self._get('item_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setTrainRatio(self, value):
        return self._set('train_ratio', value)

    def getTrainRatio(self):
        return self._get('train_ratio')

    def setUserCol(self, value):
        return self._set('user_col', value)

    def getUserCol(self):
        return self._get('user_col')


class RankingTrainValidationSplitModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.recommendation.adapter.RankingTrainValidationSplitModel``)."""

    _target = 'synapseml_tpu.recommendation.adapter.RankingTrainValidationSplitModel'

    def setBestModel(self, value):
        return self._set('best_model', value)

    def getBestModel(self):
        return self._get('best_model')

    def setValidationMetrics(self, value):
        return self._set('validation_metrics', value)

    def getValidationMetrics(self):
        return self._get('validation_metrics')


class RankingEvaluator(WrapperBase):
    """Consumes a DataFrame with per-user prediction and ground-truth item (wraps ``synapseml_tpu.recommendation.evaluator.RankingEvaluator``)."""

    _target = 'synapseml_tpu.recommendation.evaluator.RankingEvaluator'

    def setK(self, value):
        return self._set('k', value)

    def getK(self):
        return self._get('k')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setMetricName(self, value):
        return self._set('metric_name', value)

    def getMetricName(self):
        return self._get('metric_name')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')


class RecommendationIndexer(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.recommendation.indexer.RecommendationIndexer``)."""

    _target = 'synapseml_tpu.recommendation.indexer.RecommendationIndexer'

    def setItemInputCol(self, value):
        return self._set('item_input_col', value)

    def getItemInputCol(self):
        return self._get('item_input_col')

    def setItemOutputCol(self, value):
        return self._set('item_output_col', value)

    def getItemOutputCol(self):
        return self._get('item_output_col')

    def setUserInputCol(self, value):
        return self._set('user_input_col', value)

    def getUserInputCol(self):
        return self._get('user_input_col')

    def setUserOutputCol(self, value):
        return self._set('user_output_col', value)

    def getUserOutputCol(self):
        return self._get('user_output_col')


class RecommendationIndexerModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.recommendation.indexer.RecommendationIndexerModel``)."""

    _target = 'synapseml_tpu.recommendation.indexer.RecommendationIndexerModel'

    def setItemInputCol(self, value):
        return self._set('item_input_col', value)

    def getItemInputCol(self):
        return self._get('item_input_col')

    def setItemLevels(self, value):
        return self._set('item_levels', value)

    def getItemLevels(self):
        return self._get('item_levels')

    def setItemOutputCol(self, value):
        return self._set('item_output_col', value)

    def getItemOutputCol(self):
        return self._get('item_output_col')

    def setUserInputCol(self, value):
        return self._set('user_input_col', value)

    def getUserInputCol(self):
        return self._get('user_input_col')

    def setUserLevels(self, value):
        return self._set('user_levels', value)

    def getUserLevels(self):
        return self._get('user_levels')

    def setUserOutputCol(self, value):
        return self._set('user_output_col', value)

    def getUserOutputCol(self):
        return self._get('user_output_col')


class SAR(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.recommendation.sar.SAR``)."""

    _target = 'synapseml_tpu.recommendation.sar.SAR'

    def setItemCol(self, value):
        return self._set('item_col', value)

    def getItemCol(self):
        return self._get('item_col')

    def setRatingCol(self, value):
        return self._set('rating_col', value)

    def getRatingCol(self):
        return self._get('rating_col')

    def setSimilarityFunction(self, value):
        return self._set('similarity_function', value)

    def getSimilarityFunction(self):
        return self._get('similarity_function')

    def setSupportThreshold(self, value):
        return self._set('support_threshold', value)

    def getSupportThreshold(self):
        return self._get('support_threshold')

    def setTimeCol(self, value):
        return self._set('time_col', value)

    def getTimeCol(self):
        return self._get('time_col')

    def setTimeDecayCoeff(self, value):
        return self._set('time_decay_coeff', value)

    def getTimeDecayCoeff(self):
        return self._get('time_decay_coeff')

    def setUserCol(self, value):
        return self._set('user_col', value)

    def getUserCol(self):
        return self._get('user_col')


class SARModel(WrapperBase):
    """(ref ``SARModel.scala:23``) — ``recommend_for_all_users(k)`` and (wraps ``synapseml_tpu.recommendation.sar.SARModel``)."""

    _target = 'synapseml_tpu.recommendation.sar.SARModel'

    def setItemCol(self, value):
        return self._set('item_col', value)

    def getItemCol(self):
        return self._get('item_col')

    def setItemDataFrame(self, value):
        return self._set('item_data_frame', value)

    def getItemDataFrame(self):
        return self._get('item_data_frame')

    def setK(self, value):
        return self._set('k', value)

    def getK(self):
        return self._get('k')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRemoveSeen(self, value):
        return self._set('remove_seen', value)

    def getRemoveSeen(self):
        return self._get('remove_seen')

    def setSeenItems(self, value):
        return self._set('seen_items', value)

    def getSeenItems(self):
        return self._get('seen_items')

    def setUserCol(self, value):
        return self._set('user_col', value)

    def getUserCol(self):
        return self._get('user_col')

    def setUserDataFrame(self, value):
        return self._set('user_data_frame', value)

    def getUserDataFrame(self):
        return self._get('user_data_frame')

