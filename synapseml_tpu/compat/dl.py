"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class DeepTextClassifier(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.models.text.DeepTextClassifier``)."""

    _target = 'synapseml_tpu.models.text.DeepTextClassifier'

    def setAttnImpl(self, value):
        return self._set('attn_impl', value)

    def getAttnImpl(self):
        return self._get('attn_impl')

    def setBatchSize(self, value):
        return self._set('batch_size', value)

    def getBatchSize(self):
        return self._get('batch_size')

    def setCheckpoint(self, value):
        return self._set('checkpoint', value)

    def getCheckpoint(self):
        return self._get('checkpoint')

    def setCheckpointDir(self, value):
        return self._set('checkpoint_dir', value)

    def getCheckpointDir(self):
        return self._get('checkpoint_dir')

    def setCheckpointEvery(self, value):
        return self._set('checkpoint_every', value)

    def getCheckpointEvery(self):
        return self._get('checkpoint_every')

    def setCheckpointKeep(self, value):
        return self._set('checkpoint_keep', value)

    def getCheckpointKeep(self):
        return self._get('checkpoint_keep')

    def setGradAccum(self, value):
        return self._set('grad_accum', value)

    def getGradAccum(self):
        return self._get('grad_accum')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setLearningRate(self, value):
        return self._set('learning_rate', value)

    def getLearningRate(self):
        return self._get('learning_rate')

    def setMaxSteps(self, value):
        return self._set('max_steps', value)

    def getMaxSteps(self):
        return self._get('max_steps')

    def setMaxTokenLen(self, value):
        return self._set('max_token_len', value)

    def getMaxTokenLen(self):
        return self._get('max_token_len')

    def setMeshConfig(self, value):
        return self._set('mesh_config', value)

    def getMeshConfig(self):
        return self._get('mesh_config')

    def setNumClasses(self, value):
        return self._set('num_classes', value)

    def getNumClasses(self):
        return self._get('num_classes')

    def setNumTrainEpochs(self, value):
        return self._set('num_train_epochs', value)

    def getNumTrainEpochs(self):
        return self._get('num_train_epochs')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setScoresCol(self, value):
        return self._set('scores_col', value)

    def getScoresCol(self):
        return self._get('scores_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setTextCol(self, value):
        return self._set('text_col', value)

    def getTextCol(self):
        return self._get('text_col')

    def setTokenizer(self, value):
        return self._set('tokenizer', value)

    def getTokenizer(self):
        return self._get('tokenizer')

    def setUnfreezeLayers(self, value):
        return self._set('unfreeze_layers', value)

    def getUnfreezeLayers(self):
        return self._get('unfreeze_layers')

    def setWeightDecay(self, value):
        return self._set('weight_decay', value)

    def getWeightDecay(self):
        return self._get('weight_decay')


class DeepTextModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.models.text.DeepTextModel``)."""

    _target = 'synapseml_tpu.models.text.DeepTextModel'

    def setArchConfig(self, value):
        return self._set('arch_config', value)

    def getArchConfig(self):
        return self._get('arch_config')

    def setAttnImpl(self, value):
        return self._set('attn_impl', value)

    def getAttnImpl(self):
        return self._get('attn_impl')

    def setBatchSize(self, value):
        return self._set('batch_size', value)

    def getBatchSize(self):
        return self._get('batch_size')

    def setCheckpoint(self, value):
        return self._set('checkpoint', value)

    def getCheckpoint(self):
        return self._get('checkpoint')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setMaxTokenLen(self, value):
        return self._set('max_token_len', value)

    def getMaxTokenLen(self):
        return self._get('max_token_len')

    def setMeshConfig(self, value):
        return self._set('mesh_config', value)

    def getMeshConfig(self):
        return self._get('mesh_config')

    def setModelParams(self, value):
        return self._set('model_params', value)

    def getModelParams(self):
        return self._get('model_params')

    def setNumClasses(self, value):
        return self._set('num_classes', value)

    def getNumClasses(self):
        return self._get('num_classes')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setScoresCol(self, value):
        return self._set('scores_col', value)

    def getScoresCol(self):
        return self._get('scores_col')

    def setTextCol(self, value):
        return self._set('text_col', value)

    def getTextCol(self):
        return self._get('text_col')

    def setTokenizerConfig(self, value):
        return self._set('tokenizer_config', value)

    def getTokenizerConfig(self):
        return self._get('tokenizer_config')

    def setTrainMetrics(self, value):
        return self._set('train_metrics', value)

    def getTrainMetrics(self):
        return self._get('train_metrics')


class DeepVisionClassifier(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.models.vision.DeepVisionClassifier``)."""

    _target = 'synapseml_tpu.models.vision.DeepVisionClassifier'

    def setBackbone(self, value):
        return self._set('backbone', value)

    def getBackbone(self):
        return self._get('backbone')

    def setBatchSize(self, value):
        return self._set('batch_size', value)

    def getBatchSize(self):
        return self._get('batch_size')

    def setCheckpointDir(self, value):
        return self._set('checkpoint_dir', value)

    def getCheckpointDir(self):
        return self._get('checkpoint_dir')

    def setCheckpointEvery(self, value):
        return self._set('checkpoint_every', value)

    def getCheckpointEvery(self):
        return self._get('checkpoint_every')

    def setCheckpointKeep(self, value):
        return self._set('checkpoint_keep', value)

    def getCheckpointKeep(self):
        return self._get('checkpoint_keep')

    def setImageCol(self, value):
        return self._set('image_col', value)

    def getImageCol(self):
        return self._get('image_col')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setLearningRate(self, value):
        return self._set('learning_rate', value)

    def getLearningRate(self):
        return self._get('learning_rate')

    def setMaxSteps(self, value):
        return self._set('max_steps', value)

    def getMaxSteps(self):
        return self._get('max_steps')

    def setMeshConfig(self, value):
        return self._set('mesh_config', value)

    def getMeshConfig(self):
        return self._get('mesh_config')

    def setNumClasses(self, value):
        return self._set('num_classes', value)

    def getNumClasses(self):
        return self._get('num_classes')

    def setNumTrainEpochs(self, value):
        return self._set('num_train_epochs', value)

    def getNumTrainEpochs(self):
        return self._get('num_train_epochs')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setScoresCol(self, value):
        return self._set('scores_col', value)

    def getScoresCol(self):
        return self._get('scores_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')


class DeepVisionModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.models.vision.DeepVisionModel``)."""

    _target = 'synapseml_tpu.models.vision.DeepVisionModel'

    def setArchSpec(self, value):
        return self._set('arch_spec', value)

    def getArchSpec(self):
        return self._get('arch_spec')

    def setBackbone(self, value):
        return self._set('backbone', value)

    def getBackbone(self):
        return self._get('backbone')

    def setBatchSize(self, value):
        return self._set('batch_size', value)

    def getBatchSize(self):
        return self._get('batch_size')

    def setBatchStats(self, value):
        return self._set('batch_stats', value)

    def getBatchStats(self):
        return self._get('batch_stats')

    def setImageCol(self, value):
        return self._set('image_col', value)

    def getImageCol(self):
        return self._get('image_col')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setMeshConfig(self, value):
        return self._set('mesh_config', value)

    def getMeshConfig(self):
        return self._get('mesh_config')

    def setModelParams(self, value):
        return self._set('model_params', value)

    def getModelParams(self):
        return self._get('model_params')

    def setNumClasses(self, value):
        return self._set('num_classes', value)

    def getNumClasses(self):
        return self._get('num_classes')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setScoresCol(self, value):
        return self._set('scores_col', value)

    def getScoresCol(self):
        return self._get('scores_col')

    def setTrainMetrics(self, value):
        return self._set('train_metrics', value)

    def getTrainMetrics(self):
        return self._get('train_metrics')

