"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class Pipeline(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.core.pipeline.Pipeline``)."""

    _target = 'synapseml_tpu.core.pipeline.Pipeline'

    def setStages(self, value):
        return self._set('stages', value)

    def getStages(self):
        return self._get('stages')


class PipelineModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.core.pipeline.PipelineModel``)."""

    _target = 'synapseml_tpu.core.pipeline.PipelineModel'

    def setStages(self, value):
        return self._set('stages', value)

    def getStages(self):
        return self._get('stages')

