"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class BestModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.automl.tune.BestModel``)."""

    _target = 'synapseml_tpu.automl.tune.BestModel'

    def setAllResults(self, value):
        return self._set('all_results', value)

    def getAllResults(self):
        return self._get('all_results')

    def setBestMetric(self, value):
        return self._set('best_metric', value)

    def getBestMetric(self):
        return self._get('best_metric')

    def setBestModel(self, value):
        return self._set('best_model', value)

    def getBestModel(self):
        return self._get('best_model')

    def setBestParams(self, value):
        return self._set('best_params', value)

    def getBestParams(self):
        return self._get('best_params')


class FindBestModel(WrapperBase):
    """Pick the best among already-specified models by eval metric (wraps ``synapseml_tpu.automl.tune.FindBestModel``)."""

    _target = 'synapseml_tpu.automl.tune.FindBestModel'

    def setEvaluationMetric(self, value):
        return self._set('evaluation_metric', value)

    def getEvaluationMetric(self):
        return self._get('evaluation_metric')

    def setFuseTrials(self, value):
        return self._set('fuse_trials', value)

    def getFuseTrials(self):
        return self._get('fuse_trials')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setModels(self, value):
        return self._set('models', value)

    def getModels(self):
        return self._get('models')

    def setParallelism(self, value):
        return self._set('parallelism', value)

    def getParallelism(self):
        return self._get('parallelism')


class FindBestModelResult(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.automl.tune.FindBestModelResult``)."""

    _target = 'synapseml_tpu.automl.tune.FindBestModelResult'

    def setAllModelMetrics(self, value):
        return self._set('all_model_metrics', value)

    def getAllModelMetrics(self):
        return self._get('all_model_metrics')

    def setBestMetric(self, value):
        return self._set('best_metric', value)

    def getBestMetric(self):
        return self._get('best_metric')

    def setBestModel(self, value):
        return self._set('best_model', value)

    def getBestModel(self):
        return self._get('best_model')


class TuneHyperparameters(WrapperBase):
    """Random/grid search over (possibly several) learners (wraps ``synapseml_tpu.automl.tune.TuneHyperparameters``)."""

    _target = 'synapseml_tpu.automl.tune.TuneHyperparameters'

    def setEvaluationMetric(self, value):
        return self._set('evaluation_metric', value)

    def getEvaluationMetric(self):
        return self._get('evaluation_metric')

    def setFuseTrials(self, value):
        return self._set('fuse_trials', value)

    def getFuseTrials(self):
        return self._get('fuse_trials')

    def setHyperparamSpace(self, value):
        return self._set('hyperparam_space', value)

    def getHyperparamSpace(self):
        return self._get('hyperparam_space')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setModels(self, value):
        return self._set('models', value)

    def getModels(self):
        return self._get('models')

    def setNumRuns(self, value):
        return self._set('num_runs', value)

    def getNumRuns(self):
        return self._get('num_runs')

    def setParallelism(self, value):
        return self._set('parallelism', value)

    def getParallelism(self):
        return self._get('parallelism')

    def setSearchMode(self, value):
        return self._set('search_mode', value)

    def getSearchMode(self):
        return self._get('search_mode')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setValidationFraction(self, value):
        return self._set('validation_fraction', value)

    def getValidationFraction(self):
        return self._get('validation_fraction')

