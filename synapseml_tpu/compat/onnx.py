"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class ImageFeaturizer(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.onnx.featurizer.ImageFeaturizer``)."""

    _target = 'synapseml_tpu.onnx.featurizer.ImageFeaturizer'

    def setCenterCrop(self, value):
        return self._set('center_crop', value)

    def getCenterCrop(self):
        return self._get('center_crop')

    def setFeatureTensorName(self, value):
        return self._set('feature_tensor_name', value)

    def getFeatureTensorName(self):
        return self._get('feature_tensor_name')

    def setHeadLess(self, value):
        return self._set('head_less', value)

    def getHeadLess(self):
        return self._get('head_less')

    def setImageHeight(self, value):
        return self._set('image_height', value)

    def getImageHeight(self):
        return self._get('image_height')

    def setImageWidth(self, value):
        return self._set('image_width', value)

    def getImageWidth(self):
        return self._get('image_width')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setMiniBatchSize(self, value):
        return self._set('mini_batch_size', value)

    def getMiniBatchSize(self):
        return self._get('mini_batch_size')

    def setModelPayload(self, value):
        return self._set('model_payload', value)

    def getModelPayload(self):
        return self._get('model_payload')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')


class ONNXModel(WrapperBase):
    """(ref ``ONNXModel.scala:145``) (wraps ``synapseml_tpu.onnx.model.ONNXModel``)."""

    _target = 'synapseml_tpu.onnx.model.ONNXModel'

    def setArgmaxDict(self, value):
        return self._set('argmax_dict', value)

    def getArgmaxDict(self):
        return self._get('argmax_dict')

    def setFeedDict(self, value):
        return self._set('feed_dict', value)

    def getFeedDict(self):
        return self._get('feed_dict')

    def setFetchDict(self, value):
        return self._set('fetch_dict', value)

    def getFetchDict(self):
        return self._get('fetch_dict')

    def setMiniBatchSize(self, value):
        return self._set('mini_batch_size', value)

    def getMiniBatchSize(self):
        return self._get('mini_batch_size')

    def setModelPayload(self, value):
        return self._set('model_payload', value)

    def getModelPayload(self):
        return self._get('model_payload')

    def setSoftmaxDict(self, value):
        return self._set('softmax_dict', value)

    def getSoftmaxDict(self):
        return self._get('softmax_dict')

