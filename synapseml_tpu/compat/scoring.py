"""Generated passthrough namespace — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers).
Re-exports the public surface of ``synapseml_tpu.scoring`` so the compat layer covers
non-stage subsystems too (compat coverage is drift-tested).
"""


from synapseml_tpu.scoring import (  # noqa: F401
    JsonlSink,
    NpySink,
    ScoreSink,
    ScoringContractError,
    ScoringPlan,
    ScoringReport,
    assign_shards,
    iter_shard_batches,
    open_sink,
    plan_scan,
    transform_source,
)

__all__ = [
    'JsonlSink',
    'NpySink',
    'ScoreSink',
    'ScoringContractError',
    'ScoringPlan',
    'ScoringReport',
    'assign_shards',
    'iter_shard_batches',
    'open_sink',
    'plan_scan',
    'transform_source',
]
