"""Generated passthrough namespace — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers).
Re-exports the public surface of ``synapseml_tpu.rai`` so the compat layer covers
non-stage subsystems too (compat coverage is drift-tested).
"""


from synapseml_tpu.rai import (  # noqa: F401
    AuditJob,
    AuditReport,
    AuditSpec,
    DRIFT_GAUGE,
    FUSED_SCORE_FN_ID,
    MAX_FUSED_ROWS,
    array_score_fn,
    default_feature_fn,
    default_segment_fn,
    explain_source,
    fused_array_scores,
    fused_block_scores,
    fused_columnar_scores,
    js_divergence,
    psi,
    rai_measures,
    reference_bins,
    segment_drift,
)

__all__ = [
    'AuditJob',
    'AuditReport',
    'AuditSpec',
    'DRIFT_GAUGE',
    'FUSED_SCORE_FN_ID',
    'MAX_FUSED_ROWS',
    'array_score_fn',
    'default_feature_fn',
    'default_segment_fn',
    'explain_source',
    'fused_array_scores',
    'fused_block_scores',
    'fused_columnar_scores',
    'js_divergence',
    'psi',
    'rai_measures',
    'reference_bins',
    'segment_drift',
]
