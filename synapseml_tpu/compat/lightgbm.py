"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class LightGBMClassificationModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.gbdt.estimators.LightGBMClassificationModel``)."""

    _target = 'synapseml_tpu.gbdt.estimators.LightGBMClassificationModel'

    def setBaggingFraction(self, value):
        return self._set('bagging_fraction', value)

    def getBaggingFraction(self):
        return self._get('bagging_fraction')

    def setBaggingFreq(self, value):
        return self._set('bagging_freq', value)

    def getBaggingFreq(self):
        return self._get('bagging_freq')

    def setBooster(self, value):
        return self._set('booster', value)

    def getBooster(self):
        return self._get('booster')

    def setBoostingType(self, value):
        return self._set('boosting_type', value)

    def getBoostingType(self):
        return self._get('boosting_type')

    def setCategoricalSlotIndexes(self, value):
        return self._set('categorical_slot_indexes', value)

    def getCategoricalSlotIndexes(self):
        return self._get('categorical_slot_indexes')

    def setClasses(self, value):
        return self._set('classes', value)

    def getClasses(self):
        return self._get('classes')

    def setDropRate(self, value):
        return self._set('drop_rate', value)

    def getDropRate(self):
        return self._get('drop_rate')

    def setEarlyStoppingRound(self, value):
        return self._set('early_stopping_round', value)

    def getEarlyStoppingRound(self):
        return self._get('early_stopping_round')

    def setFeatureCols(self, value):
        return self._set('feature_cols', value)

    def getFeatureCols(self):
        return self._get('feature_cols')

    def setFeatureFraction(self, value):
        return self._set('feature_fraction', value)

    def getFeatureFraction(self):
        return self._get('feature_fraction')

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setFeaturesShapCol(self, value):
        return self._set('features_shap_col', value)

    def getFeaturesShapCol(self):
        return self._get('features_shap_col')

    def setHistogramImpl(self, value):
        return self._set('histogram_impl', value)

    def getHistogramImpl(self):
        return self._get('histogram_impl')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setLambdaL1(self, value):
        return self._set('lambda_l1', value)

    def getLambdaL1(self):
        return self._get('lambda_l1')

    def setLambdaL2(self, value):
        return self._set('lambda_l2', value)

    def getLambdaL2(self):
        return self._get('lambda_l2')

    def setLearningRate(self, value):
        return self._set('learning_rate', value)

    def getLearningRate(self):
        return self._get('learning_rate')

    def setMaxBin(self, value):
        return self._set('max_bin', value)

    def getMaxBin(self):
        return self._get('max_bin')

    def setMaxDepth(self, value):
        return self._set('max_depth', value)

    def getMaxDepth(self):
        return self._get('max_depth')

    def setMaxDrop(self, value):
        return self._set('max_drop', value)

    def getMaxDrop(self):
        return self._get('max_drop')

    def setMeshConfig(self, value):
        return self._set('mesh_config', value)

    def getMeshConfig(self):
        return self._get('mesh_config')

    def setMinDataInLeaf(self, value):
        return self._set('min_data_in_leaf', value)

    def getMinDataInLeaf(self):
        return self._get('min_data_in_leaf')

    def setMinGainToSplit(self, value):
        return self._set('min_gain_to_split', value)

    def getMinGainToSplit(self):
        return self._get('min_gain_to_split')

    def setMinSumHessianInLeaf(self, value):
        return self._set('min_sum_hessian_in_leaf', value)

    def getMinSumHessianInLeaf(self):
        return self._get('min_sum_hessian_in_leaf')

    def setModelString(self, value):
        return self._set('model_string', value)

    def getModelString(self):
        return self._get('model_string')

    def setMonotoneConstraints(self, value):
        return self._set('monotone_constraints', value)

    def getMonotoneConstraints(self):
        return self._get('monotone_constraints')

    def setNumIterations(self, value):
        return self._set('num_iterations', value)

    def getNumIterations(self):
        return self._get('num_iterations')

    def setNumLeaves(self, value):
        return self._set('num_leaves', value)

    def getNumLeaves(self):
        return self._get('num_leaves')

    def setOtherRate(self, value):
        return self._set('other_rate', value)

    def getOtherRate(self):
        return self._get('other_rate')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setProbabilityCol(self, value):
        return self._set('probability_col', value)

    def getProbabilityCol(self):
        return self._get('probability_col')

    def setRawPredictionCol(self, value):
        return self._set('raw_prediction_col', value)

    def getRawPredictionCol(self):
        return self._get('raw_prediction_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setSkipDrop(self, value):
        return self._set('skip_drop', value)

    def getSkipDrop(self):
        return self._get('skip_drop')

    def setTopRate(self, value):
        return self._set('top_rate', value)

    def getTopRate(self):
        return self._get('top_rate')

    def setValidationIndicatorCol(self, value):
        return self._set('validation_indicator_col', value)

    def getValidationIndicatorCol(self):
        return self._get('validation_indicator_col')

    def setVerbosity(self, value):
        return self._set('verbosity', value)

    def getVerbosity(self):
        return self._get('verbosity')

    def setWeightCol(self, value):
        return self._set('weight_col', value)

    def getWeightCol(self):
        return self._get('weight_col')


class LightGBMClassifier(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.gbdt.estimators.LightGBMClassifier``)."""

    _target = 'synapseml_tpu.gbdt.estimators.LightGBMClassifier'

    def setBaggingFraction(self, value):
        return self._set('bagging_fraction', value)

    def getBaggingFraction(self):
        return self._get('bagging_fraction')

    def setBaggingFreq(self, value):
        return self._set('bagging_freq', value)

    def getBaggingFreq(self):
        return self._get('bagging_freq')

    def setBoostingType(self, value):
        return self._set('boosting_type', value)

    def getBoostingType(self):
        return self._get('boosting_type')

    def setCategoricalSlotIndexes(self, value):
        return self._set('categorical_slot_indexes', value)

    def getCategoricalSlotIndexes(self):
        return self._get('categorical_slot_indexes')

    def setDropRate(self, value):
        return self._set('drop_rate', value)

    def getDropRate(self):
        return self._get('drop_rate')

    def setEarlyStoppingRound(self, value):
        return self._set('early_stopping_round', value)

    def getEarlyStoppingRound(self):
        return self._get('early_stopping_round')

    def setFeatureCols(self, value):
        return self._set('feature_cols', value)

    def getFeatureCols(self):
        return self._get('feature_cols')

    def setFeatureFraction(self, value):
        return self._set('feature_fraction', value)

    def getFeatureFraction(self):
        return self._get('feature_fraction')

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setHistogramImpl(self, value):
        return self._set('histogram_impl', value)

    def getHistogramImpl(self):
        return self._get('histogram_impl')

    def setIsUnbalance(self, value):
        return self._set('is_unbalance', value)

    def getIsUnbalance(self):
        return self._get('is_unbalance')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setLambdaL1(self, value):
        return self._set('lambda_l1', value)

    def getLambdaL1(self):
        return self._get('lambda_l1')

    def setLambdaL2(self, value):
        return self._set('lambda_l2', value)

    def getLambdaL2(self):
        return self._get('lambda_l2')

    def setLearningRate(self, value):
        return self._set('learning_rate', value)

    def getLearningRate(self):
        return self._get('learning_rate')

    def setMaxBin(self, value):
        return self._set('max_bin', value)

    def getMaxBin(self):
        return self._get('max_bin')

    def setMaxDepth(self, value):
        return self._set('max_depth', value)

    def getMaxDepth(self):
        return self._get('max_depth')

    def setMaxDrop(self, value):
        return self._set('max_drop', value)

    def getMaxDrop(self):
        return self._get('max_drop')

    def setMeshConfig(self, value):
        return self._set('mesh_config', value)

    def getMeshConfig(self):
        return self._get('mesh_config')

    def setMinDataInLeaf(self, value):
        return self._set('min_data_in_leaf', value)

    def getMinDataInLeaf(self):
        return self._get('min_data_in_leaf')

    def setMinGainToSplit(self, value):
        return self._set('min_gain_to_split', value)

    def getMinGainToSplit(self):
        return self._get('min_gain_to_split')

    def setMinSumHessianInLeaf(self, value):
        return self._set('min_sum_hessian_in_leaf', value)

    def getMinSumHessianInLeaf(self):
        return self._get('min_sum_hessian_in_leaf')

    def setModelString(self, value):
        return self._set('model_string', value)

    def getModelString(self):
        return self._get('model_string')

    def setMonotoneConstraints(self, value):
        return self._set('monotone_constraints', value)

    def getMonotoneConstraints(self):
        return self._get('monotone_constraints')

    def setNumIterations(self, value):
        return self._set('num_iterations', value)

    def getNumIterations(self):
        return self._get('num_iterations')

    def setNumLeaves(self, value):
        return self._set('num_leaves', value)

    def getNumLeaves(self):
        return self._get('num_leaves')

    def setObjective(self, value):
        return self._set('objective', value)

    def getObjective(self):
        return self._get('objective')

    def setOtherRate(self, value):
        return self._set('other_rate', value)

    def getOtherRate(self):
        return self._get('other_rate')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setProbabilityCol(self, value):
        return self._set('probability_col', value)

    def getProbabilityCol(self):
        return self._get('probability_col')

    def setRawPredictionCol(self, value):
        return self._set('raw_prediction_col', value)

    def getRawPredictionCol(self):
        return self._get('raw_prediction_col')

    def setScalePosWeight(self, value):
        return self._set('scale_pos_weight', value)

    def getScalePosWeight(self):
        return self._get('scale_pos_weight')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setSkipDrop(self, value):
        return self._set('skip_drop', value)

    def getSkipDrop(self):
        return self._get('skip_drop')

    def setTopRate(self, value):
        return self._set('top_rate', value)

    def getTopRate(self):
        return self._get('top_rate')

    def setValidationIndicatorCol(self, value):
        return self._set('validation_indicator_col', value)

    def getValidationIndicatorCol(self):
        return self._get('validation_indicator_col')

    def setVerbosity(self, value):
        return self._set('verbosity', value)

    def getVerbosity(self):
        return self._get('verbosity')

    def setWeightCol(self, value):
        return self._set('weight_col', value)

    def getWeightCol(self):
        return self._get('weight_col')


class LightGBMRanker(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.gbdt.estimators.LightGBMRanker``)."""

    _target = 'synapseml_tpu.gbdt.estimators.LightGBMRanker'

    def setBaggingFraction(self, value):
        return self._set('bagging_fraction', value)

    def getBaggingFraction(self):
        return self._get('bagging_fraction')

    def setBaggingFreq(self, value):
        return self._set('bagging_freq', value)

    def getBaggingFreq(self):
        return self._get('bagging_freq')

    def setBoostingType(self, value):
        return self._set('boosting_type', value)

    def getBoostingType(self):
        return self._get('boosting_type')

    def setCategoricalSlotIndexes(self, value):
        return self._set('categorical_slot_indexes', value)

    def getCategoricalSlotIndexes(self):
        return self._get('categorical_slot_indexes')

    def setDropRate(self, value):
        return self._set('drop_rate', value)

    def getDropRate(self):
        return self._get('drop_rate')

    def setEarlyStoppingRound(self, value):
        return self._set('early_stopping_round', value)

    def getEarlyStoppingRound(self):
        return self._get('early_stopping_round')

    def setEvalAt(self, value):
        return self._set('eval_at', value)

    def getEvalAt(self):
        return self._get('eval_at')

    def setFeatureCols(self, value):
        return self._set('feature_cols', value)

    def getFeatureCols(self):
        return self._get('feature_cols')

    def setFeatureFraction(self, value):
        return self._set('feature_fraction', value)

    def getFeatureFraction(self):
        return self._get('feature_fraction')

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setGroupCol(self, value):
        return self._set('group_col', value)

    def getGroupCol(self):
        return self._get('group_col')

    def setHistogramImpl(self, value):
        return self._set('histogram_impl', value)

    def getHistogramImpl(self):
        return self._get('histogram_impl')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setLambdaL1(self, value):
        return self._set('lambda_l1', value)

    def getLambdaL1(self):
        return self._get('lambda_l1')

    def setLambdaL2(self, value):
        return self._set('lambda_l2', value)

    def getLambdaL2(self):
        return self._get('lambda_l2')

    def setLearningRate(self, value):
        return self._set('learning_rate', value)

    def getLearningRate(self):
        return self._get('learning_rate')

    def setMaxBin(self, value):
        return self._set('max_bin', value)

    def getMaxBin(self):
        return self._get('max_bin')

    def setMaxDepth(self, value):
        return self._set('max_depth', value)

    def getMaxDepth(self):
        return self._get('max_depth')

    def setMaxDrop(self, value):
        return self._set('max_drop', value)

    def getMaxDrop(self):
        return self._get('max_drop')

    def setMeshConfig(self, value):
        return self._set('mesh_config', value)

    def getMeshConfig(self):
        return self._get('mesh_config')

    def setMinDataInLeaf(self, value):
        return self._set('min_data_in_leaf', value)

    def getMinDataInLeaf(self):
        return self._get('min_data_in_leaf')

    def setMinGainToSplit(self, value):
        return self._set('min_gain_to_split', value)

    def getMinGainToSplit(self):
        return self._get('min_gain_to_split')

    def setMinSumHessianInLeaf(self, value):
        return self._set('min_sum_hessian_in_leaf', value)

    def getMinSumHessianInLeaf(self):
        return self._get('min_sum_hessian_in_leaf')

    def setModelString(self, value):
        return self._set('model_string', value)

    def getModelString(self):
        return self._get('model_string')

    def setMonotoneConstraints(self, value):
        return self._set('monotone_constraints', value)

    def getMonotoneConstraints(self):
        return self._get('monotone_constraints')

    def setNumIterations(self, value):
        return self._set('num_iterations', value)

    def getNumIterations(self):
        return self._get('num_iterations')

    def setNumLeaves(self, value):
        return self._set('num_leaves', value)

    def getNumLeaves(self):
        return self._get('num_leaves')

    def setOtherRate(self, value):
        return self._set('other_rate', value)

    def getOtherRate(self):
        return self._get('other_rate')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setSkipDrop(self, value):
        return self._set('skip_drop', value)

    def getSkipDrop(self):
        return self._get('skip_drop')

    def setTopRate(self, value):
        return self._set('top_rate', value)

    def getTopRate(self):
        return self._get('top_rate')

    def setValidationIndicatorCol(self, value):
        return self._set('validation_indicator_col', value)

    def getValidationIndicatorCol(self):
        return self._get('validation_indicator_col')

    def setVerbosity(self, value):
        return self._set('verbosity', value)

    def getVerbosity(self):
        return self._get('verbosity')

    def setWeightCol(self, value):
        return self._set('weight_col', value)

    def getWeightCol(self):
        return self._get('weight_col')


class LightGBMRankerModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.gbdt.estimators.LightGBMRankerModel``)."""

    _target = 'synapseml_tpu.gbdt.estimators.LightGBMRankerModel'

    def setBaggingFraction(self, value):
        return self._set('bagging_fraction', value)

    def getBaggingFraction(self):
        return self._get('bagging_fraction')

    def setBaggingFreq(self, value):
        return self._set('bagging_freq', value)

    def getBaggingFreq(self):
        return self._get('bagging_freq')

    def setBooster(self, value):
        return self._set('booster', value)

    def getBooster(self):
        return self._get('booster')

    def setBoostingType(self, value):
        return self._set('boosting_type', value)

    def getBoostingType(self):
        return self._get('boosting_type')

    def setCategoricalSlotIndexes(self, value):
        return self._set('categorical_slot_indexes', value)

    def getCategoricalSlotIndexes(self):
        return self._get('categorical_slot_indexes')

    def setDropRate(self, value):
        return self._set('drop_rate', value)

    def getDropRate(self):
        return self._get('drop_rate')

    def setEarlyStoppingRound(self, value):
        return self._set('early_stopping_round', value)

    def getEarlyStoppingRound(self):
        return self._get('early_stopping_round')

    def setFeatureCols(self, value):
        return self._set('feature_cols', value)

    def getFeatureCols(self):
        return self._get('feature_cols')

    def setFeatureFraction(self, value):
        return self._set('feature_fraction', value)

    def getFeatureFraction(self):
        return self._get('feature_fraction')

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setFeaturesShapCol(self, value):
        return self._set('features_shap_col', value)

    def getFeaturesShapCol(self):
        return self._get('features_shap_col')

    def setHistogramImpl(self, value):
        return self._set('histogram_impl', value)

    def getHistogramImpl(self):
        return self._get('histogram_impl')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setLambdaL1(self, value):
        return self._set('lambda_l1', value)

    def getLambdaL1(self):
        return self._get('lambda_l1')

    def setLambdaL2(self, value):
        return self._set('lambda_l2', value)

    def getLambdaL2(self):
        return self._get('lambda_l2')

    def setLearningRate(self, value):
        return self._set('learning_rate', value)

    def getLearningRate(self):
        return self._get('learning_rate')

    def setMaxBin(self, value):
        return self._set('max_bin', value)

    def getMaxBin(self):
        return self._get('max_bin')

    def setMaxDepth(self, value):
        return self._set('max_depth', value)

    def getMaxDepth(self):
        return self._get('max_depth')

    def setMaxDrop(self, value):
        return self._set('max_drop', value)

    def getMaxDrop(self):
        return self._get('max_drop')

    def setMeshConfig(self, value):
        return self._set('mesh_config', value)

    def getMeshConfig(self):
        return self._get('mesh_config')

    def setMinDataInLeaf(self, value):
        return self._set('min_data_in_leaf', value)

    def getMinDataInLeaf(self):
        return self._get('min_data_in_leaf')

    def setMinGainToSplit(self, value):
        return self._set('min_gain_to_split', value)

    def getMinGainToSplit(self):
        return self._get('min_gain_to_split')

    def setMinSumHessianInLeaf(self, value):
        return self._set('min_sum_hessian_in_leaf', value)

    def getMinSumHessianInLeaf(self):
        return self._get('min_sum_hessian_in_leaf')

    def setModelString(self, value):
        return self._set('model_string', value)

    def getModelString(self):
        return self._get('model_string')

    def setMonotoneConstraints(self, value):
        return self._set('monotone_constraints', value)

    def getMonotoneConstraints(self):
        return self._get('monotone_constraints')

    def setNumIterations(self, value):
        return self._set('num_iterations', value)

    def getNumIterations(self):
        return self._get('num_iterations')

    def setNumLeaves(self, value):
        return self._set('num_leaves', value)

    def getNumLeaves(self):
        return self._get('num_leaves')

    def setOtherRate(self, value):
        return self._set('other_rate', value)

    def getOtherRate(self):
        return self._get('other_rate')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setSkipDrop(self, value):
        return self._set('skip_drop', value)

    def getSkipDrop(self):
        return self._get('skip_drop')

    def setTopRate(self, value):
        return self._set('top_rate', value)

    def getTopRate(self):
        return self._get('top_rate')

    def setValidationIndicatorCol(self, value):
        return self._set('validation_indicator_col', value)

    def getValidationIndicatorCol(self):
        return self._get('validation_indicator_col')

    def setVerbosity(self, value):
        return self._set('verbosity', value)

    def getVerbosity(self):
        return self._get('verbosity')

    def setWeightCol(self, value):
        return self._set('weight_col', value)

    def getWeightCol(self):
        return self._get('weight_col')


class LightGBMRegressionModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.gbdt.estimators.LightGBMRegressionModel``)."""

    _target = 'synapseml_tpu.gbdt.estimators.LightGBMRegressionModel'

    def setBaggingFraction(self, value):
        return self._set('bagging_fraction', value)

    def getBaggingFraction(self):
        return self._get('bagging_fraction')

    def setBaggingFreq(self, value):
        return self._set('bagging_freq', value)

    def getBaggingFreq(self):
        return self._get('bagging_freq')

    def setBooster(self, value):
        return self._set('booster', value)

    def getBooster(self):
        return self._get('booster')

    def setBoostingType(self, value):
        return self._set('boosting_type', value)

    def getBoostingType(self):
        return self._get('boosting_type')

    def setCategoricalSlotIndexes(self, value):
        return self._set('categorical_slot_indexes', value)

    def getCategoricalSlotIndexes(self):
        return self._get('categorical_slot_indexes')

    def setDropRate(self, value):
        return self._set('drop_rate', value)

    def getDropRate(self):
        return self._get('drop_rate')

    def setEarlyStoppingRound(self, value):
        return self._set('early_stopping_round', value)

    def getEarlyStoppingRound(self):
        return self._get('early_stopping_round')

    def setFeatureCols(self, value):
        return self._set('feature_cols', value)

    def getFeatureCols(self):
        return self._get('feature_cols')

    def setFeatureFraction(self, value):
        return self._set('feature_fraction', value)

    def getFeatureFraction(self):
        return self._get('feature_fraction')

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setFeaturesShapCol(self, value):
        return self._set('features_shap_col', value)

    def getFeaturesShapCol(self):
        return self._get('features_shap_col')

    def setHistogramImpl(self, value):
        return self._set('histogram_impl', value)

    def getHistogramImpl(self):
        return self._get('histogram_impl')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setLambdaL1(self, value):
        return self._set('lambda_l1', value)

    def getLambdaL1(self):
        return self._get('lambda_l1')

    def setLambdaL2(self, value):
        return self._set('lambda_l2', value)

    def getLambdaL2(self):
        return self._get('lambda_l2')

    def setLearningRate(self, value):
        return self._set('learning_rate', value)

    def getLearningRate(self):
        return self._get('learning_rate')

    def setMaxBin(self, value):
        return self._set('max_bin', value)

    def getMaxBin(self):
        return self._get('max_bin')

    def setMaxDepth(self, value):
        return self._set('max_depth', value)

    def getMaxDepth(self):
        return self._get('max_depth')

    def setMaxDrop(self, value):
        return self._set('max_drop', value)

    def getMaxDrop(self):
        return self._get('max_drop')

    def setMeshConfig(self, value):
        return self._set('mesh_config', value)

    def getMeshConfig(self):
        return self._get('mesh_config')

    def setMinDataInLeaf(self, value):
        return self._set('min_data_in_leaf', value)

    def getMinDataInLeaf(self):
        return self._get('min_data_in_leaf')

    def setMinGainToSplit(self, value):
        return self._set('min_gain_to_split', value)

    def getMinGainToSplit(self):
        return self._get('min_gain_to_split')

    def setMinSumHessianInLeaf(self, value):
        return self._set('min_sum_hessian_in_leaf', value)

    def getMinSumHessianInLeaf(self):
        return self._get('min_sum_hessian_in_leaf')

    def setModelString(self, value):
        return self._set('model_string', value)

    def getModelString(self):
        return self._get('model_string')

    def setMonotoneConstraints(self, value):
        return self._set('monotone_constraints', value)

    def getMonotoneConstraints(self):
        return self._get('monotone_constraints')

    def setNumIterations(self, value):
        return self._set('num_iterations', value)

    def getNumIterations(self):
        return self._get('num_iterations')

    def setNumLeaves(self, value):
        return self._set('num_leaves', value)

    def getNumLeaves(self):
        return self._get('num_leaves')

    def setOtherRate(self, value):
        return self._set('other_rate', value)

    def getOtherRate(self):
        return self._get('other_rate')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setSkipDrop(self, value):
        return self._set('skip_drop', value)

    def getSkipDrop(self):
        return self._get('skip_drop')

    def setTopRate(self, value):
        return self._set('top_rate', value)

    def getTopRate(self):
        return self._get('top_rate')

    def setValidationIndicatorCol(self, value):
        return self._set('validation_indicator_col', value)

    def getValidationIndicatorCol(self):
        return self._get('validation_indicator_col')

    def setVerbosity(self, value):
        return self._set('verbosity', value)

    def getVerbosity(self):
        return self._get('verbosity')

    def setWeightCol(self, value):
        return self._set('weight_col', value)

    def getWeightCol(self):
        return self._get('weight_col')


class LightGBMRegressor(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.gbdt.estimators.LightGBMRegressor``)."""

    _target = 'synapseml_tpu.gbdt.estimators.LightGBMRegressor'

    def setAlpha(self, value):
        return self._set('alpha', value)

    def getAlpha(self):
        return self._get('alpha')

    def setBaggingFraction(self, value):
        return self._set('bagging_fraction', value)

    def getBaggingFraction(self):
        return self._get('bagging_fraction')

    def setBaggingFreq(self, value):
        return self._set('bagging_freq', value)

    def getBaggingFreq(self):
        return self._get('bagging_freq')

    def setBoostingType(self, value):
        return self._set('boosting_type', value)

    def getBoostingType(self):
        return self._get('boosting_type')

    def setCategoricalSlotIndexes(self, value):
        return self._set('categorical_slot_indexes', value)

    def getCategoricalSlotIndexes(self):
        return self._get('categorical_slot_indexes')

    def setDropRate(self, value):
        return self._set('drop_rate', value)

    def getDropRate(self):
        return self._get('drop_rate')

    def setEarlyStoppingRound(self, value):
        return self._set('early_stopping_round', value)

    def getEarlyStoppingRound(self):
        return self._get('early_stopping_round')

    def setFeatureCols(self, value):
        return self._set('feature_cols', value)

    def getFeatureCols(self):
        return self._get('feature_cols')

    def setFeatureFraction(self, value):
        return self._set('feature_fraction', value)

    def getFeatureFraction(self):
        return self._get('feature_fraction')

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setHistogramImpl(self, value):
        return self._set('histogram_impl', value)

    def getHistogramImpl(self):
        return self._get('histogram_impl')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setLambdaL1(self, value):
        return self._set('lambda_l1', value)

    def getLambdaL1(self):
        return self._get('lambda_l1')

    def setLambdaL2(self, value):
        return self._set('lambda_l2', value)

    def getLambdaL2(self):
        return self._get('lambda_l2')

    def setLearningRate(self, value):
        return self._set('learning_rate', value)

    def getLearningRate(self):
        return self._get('learning_rate')

    def setMaxBin(self, value):
        return self._set('max_bin', value)

    def getMaxBin(self):
        return self._get('max_bin')

    def setMaxDepth(self, value):
        return self._set('max_depth', value)

    def getMaxDepth(self):
        return self._get('max_depth')

    def setMaxDrop(self, value):
        return self._set('max_drop', value)

    def getMaxDrop(self):
        return self._get('max_drop')

    def setMeshConfig(self, value):
        return self._set('mesh_config', value)

    def getMeshConfig(self):
        return self._get('mesh_config')

    def setMinDataInLeaf(self, value):
        return self._set('min_data_in_leaf', value)

    def getMinDataInLeaf(self):
        return self._get('min_data_in_leaf')

    def setMinGainToSplit(self, value):
        return self._set('min_gain_to_split', value)

    def getMinGainToSplit(self):
        return self._get('min_gain_to_split')

    def setMinSumHessianInLeaf(self, value):
        return self._set('min_sum_hessian_in_leaf', value)

    def getMinSumHessianInLeaf(self):
        return self._get('min_sum_hessian_in_leaf')

    def setModelString(self, value):
        return self._set('model_string', value)

    def getModelString(self):
        return self._get('model_string')

    def setMonotoneConstraints(self, value):
        return self._set('monotone_constraints', value)

    def getMonotoneConstraints(self):
        return self._get('monotone_constraints')

    def setNumIterations(self, value):
        return self._set('num_iterations', value)

    def getNumIterations(self):
        return self._get('num_iterations')

    def setNumLeaves(self, value):
        return self._set('num_leaves', value)

    def getNumLeaves(self):
        return self._get('num_leaves')

    def setObjective(self, value):
        return self._set('objective', value)

    def getObjective(self):
        return self._get('objective')

    def setOtherRate(self, value):
        return self._set('other_rate', value)

    def getOtherRate(self):
        return self._get('other_rate')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setSkipDrop(self, value):
        return self._set('skip_drop', value)

    def getSkipDrop(self):
        return self._get('skip_drop')

    def setTopRate(self, value):
        return self._set('top_rate', value)

    def getTopRate(self):
        return self._get('top_rate')

    def setTweedieVariancePower(self, value):
        return self._set('tweedie_variance_power', value)

    def getTweedieVariancePower(self):
        return self._get('tweedie_variance_power')

    def setValidationIndicatorCol(self, value):
        return self._set('validation_indicator_col', value)

    def getValidationIndicatorCol(self):
        return self._get('validation_indicator_col')

    def setVerbosity(self, value):
        return self._set('verbosity', value)

    def getVerbosity(self):
        return self._get('verbosity')

    def setWeightCol(self, value):
        return self._set('weight_col', value)

    def getWeightCol(self):
        return self._get('weight_col')

