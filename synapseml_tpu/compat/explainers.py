"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class LocalExplainerBase(WrapperBase):
    """Common params + the one-shot scoring path: ALL samples for a partition (wraps ``synapseml_tpu.explainers.base.LocalExplainerBase``)."""

    _target = 'synapseml_tpu.explainers.base.LocalExplainerBase'

    def setFused(self, value):
        return self._set('fused', value)

    def getFused(self):
        return self._get('fused')

    def setModel(self, value):
        return self._set('model', value)

    def getModel(self):
        return self._get('model')

    def setNumSamples(self, value):
        return self._set('num_samples', value)

    def getNumSamples(self):
        return self._get('num_samples')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setTargetClasses(self, value):
        return self._set('target_classes', value)

    def getTargetClasses(self):
        return self._get('target_classes')

    def setTargetCol(self, value):
        return self._set('target_col', value)

    def getTargetCol(self):
        return self._get('target_col')


class ICETransformer(WrapperBase):
    """Common params + the one-shot scoring path: ALL samples for a partition (wraps ``synapseml_tpu.explainers.ice.ICETransformer``)."""

    _target = 'synapseml_tpu.explainers.ice.ICETransformer'

    def setCategoricalFeatures(self, value):
        return self._set('categorical_features', value)

    def getCategoricalFeatures(self):
        return self._get('categorical_features')

    def setFused(self, value):
        return self._set('fused', value)

    def getFused(self):
        return self._get('fused')

    def setKind(self, value):
        return self._set('kind', value)

    def getKind(self):
        return self._get('kind')

    def setModel(self, value):
        return self._set('model', value)

    def getModel(self):
        return self._get('model')

    def setNumSamples(self, value):
        return self._set('num_samples', value)

    def getNumSamples(self):
        return self._get('num_samples')

    def setNumSplits(self, value):
        return self._set('num_splits', value)

    def getNumSplits(self):
        return self._get('num_splits')

    def setNumericFeatures(self, value):
        return self._set('numeric_features', value)

    def getNumericFeatures(self):
        return self._get('numeric_features')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setTargetClasses(self, value):
        return self._set('target_classes', value)

    def getTargetClasses(self):
        return self._get('target_classes')

    def setTargetCol(self, value):
        return self._set('target_col', value)

    def getTargetCol(self):
        return self._get('target_col')


class ImageLIME(WrapperBase):
    """(ref ``ImageLIME.scala``) superpixel on/off perturbations; the binary (wraps ``synapseml_tpu.explainers.lime.ImageLIME``)."""

    _target = 'synapseml_tpu.explainers.lime.ImageLIME'

    def setCellSize(self, value):
        return self._set('cell_size', value)

    def getCellSize(self):
        return self._get('cell_size')

    def setFused(self, value):
        return self._set('fused', value)

    def getFused(self):
        return self._get('fused')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setKernelWidth(self, value):
        return self._set('kernel_width', value)

    def getKernelWidth(self):
        return self._get('kernel_width')

    def setModel(self, value):
        return self._set('model', value)

    def getModel(self):
        return self._get('model')

    def setModifier(self, value):
        return self._set('modifier', value)

    def getModifier(self):
        return self._get('modifier')

    def setNumSamples(self, value):
        return self._set('num_samples', value)

    def getNumSamples(self):
        return self._get('num_samples')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRegularization(self, value):
        return self._set('regularization', value)

    def getRegularization(self):
        return self._get('regularization')

    def setSamplingFraction(self, value):
        return self._set('sampling_fraction', value)

    def getSamplingFraction(self):
        return self._get('sampling_fraction')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setSuperpixelCol(self, value):
        return self._set('superpixel_col', value)

    def getSuperpixelCol(self):
        return self._get('superpixel_col')

    def setTargetClasses(self, value):
        return self._set('target_classes', value)

    def getTargetClasses(self):
        return self._get('target_classes')

    def setTargetCol(self, value):
        return self._set('target_col', value)

    def getTargetCol(self):
        return self._get('target_col')


class TabularLIME(WrapperBase):
    """(ref ``TabularLIME.scala``) like VectorLIME but over named numeric (wraps ``synapseml_tpu.explainers.lime.TabularLIME``)."""

    _target = 'synapseml_tpu.explainers.lime.TabularLIME'

    def setBackgroundData(self, value):
        return self._set('background_data', value)

    def getBackgroundData(self):
        return self._get('background_data')

    def setFused(self, value):
        return self._set('fused', value)

    def getFused(self):
        return self._get('fused')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setInputCols(self, value):
        return self._set('input_cols', value)

    def getInputCols(self):
        return self._get('input_cols')

    def setKernelWidth(self, value):
        return self._set('kernel_width', value)

    def getKernelWidth(self):
        return self._get('kernel_width')

    def setModel(self, value):
        return self._set('model', value)

    def getModel(self):
        return self._get('model')

    def setNumSamples(self, value):
        return self._set('num_samples', value)

    def getNumSamples(self):
        return self._get('num_samples')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRegularization(self, value):
        return self._set('regularization', value)

    def getRegularization(self):
        return self._get('regularization')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setTargetClasses(self, value):
        return self._set('target_classes', value)

    def getTargetClasses(self):
        return self._get('target_classes')

    def setTargetCol(self, value):
        return self._set('target_col', value)

    def getTargetCol(self):
        return self._get('target_col')


class TextLIME(WrapperBase):
    """(ref ``TextLIME.scala``) token on/off perturbations. (wraps ``synapseml_tpu.explainers.lime.TextLIME``)."""

    _target = 'synapseml_tpu.explainers.lime.TextLIME'

    def setFused(self, value):
        return self._set('fused', value)

    def getFused(self):
        return self._get('fused')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setKernelWidth(self, value):
        return self._set('kernel_width', value)

    def getKernelWidth(self):
        return self._get('kernel_width')

    def setModel(self, value):
        return self._set('model', value)

    def getModel(self):
        return self._get('model')

    def setNumSamples(self, value):
        return self._set('num_samples', value)

    def getNumSamples(self):
        return self._get('num_samples')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRegularization(self, value):
        return self._set('regularization', value)

    def getRegularization(self):
        return self._get('regularization')

    def setSamplingFraction(self, value):
        return self._set('sampling_fraction', value)

    def getSamplingFraction(self):
        return self._get('sampling_fraction')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setTargetClasses(self, value):
        return self._set('target_classes', value)

    def getTargetClasses(self):
        return self._get('target_classes')

    def setTargetCol(self, value):
        return self._set('target_col', value)

    def getTargetCol(self):
        return self._get('target_col')

    def setTokenCol(self, value):
        return self._set('token_col', value)

    def getTokenCol(self):
        return self._get('token_col')


class VectorLIME(WrapperBase):
    """(ref ``VectorLIME.scala``) rows hold fixed-length feature vectors; (wraps ``synapseml_tpu.explainers.lime.VectorLIME``)."""

    _target = 'synapseml_tpu.explainers.lime.VectorLIME'

    def setBackgroundData(self, value):
        return self._set('background_data', value)

    def getBackgroundData(self):
        return self._get('background_data')

    def setFused(self, value):
        return self._set('fused', value)

    def getFused(self):
        return self._get('fused')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setKernelWidth(self, value):
        return self._set('kernel_width', value)

    def getKernelWidth(self):
        return self._get('kernel_width')

    def setModel(self, value):
        return self._set('model', value)

    def getModel(self):
        return self._get('model')

    def setNumSamples(self, value):
        return self._set('num_samples', value)

    def getNumSamples(self):
        return self._get('num_samples')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRegularization(self, value):
        return self._set('regularization', value)

    def getRegularization(self):
        return self._get('regularization')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setTargetClasses(self, value):
        return self._set('target_classes', value)

    def getTargetClasses(self):
        return self._get('target_classes')

    def setTargetCol(self, value):
        return self._set('target_col', value)

    def getTargetCol(self):
        return self._get('target_col')


class ImageSHAP(WrapperBase):
    """(ref ``ImageSHAP.scala``) superpixels as players; off superpixels (wraps ``synapseml_tpu.explainers.shap.ImageSHAP``)."""

    _target = 'synapseml_tpu.explainers.shap.ImageSHAP'

    def setCellSize(self, value):
        return self._set('cell_size', value)

    def getCellSize(self):
        return self._get('cell_size')

    def setFused(self, value):
        return self._set('fused', value)

    def getFused(self):
        return self._get('fused')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setModel(self, value):
        return self._set('model', value)

    def getModel(self):
        return self._get('model')

    def setModifier(self, value):
        return self._set('modifier', value)

    def getModifier(self):
        return self._get('modifier')

    def setNumSamples(self, value):
        return self._set('num_samples', value)

    def getNumSamples(self):
        return self._get('num_samples')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setTargetClasses(self, value):
        return self._set('target_classes', value)

    def getTargetClasses(self):
        return self._get('target_classes')

    def setTargetCol(self, value):
        return self._set('target_col', value)

    def getTargetCol(self):
        return self._get('target_col')


class TabularSHAP(WrapperBase):
    """(ref ``TabularSHAP.scala``) named numeric columns. (wraps ``synapseml_tpu.explainers.shap.TabularSHAP``)."""

    _target = 'synapseml_tpu.explainers.shap.TabularSHAP'

    def setBackgroundData(self, value):
        return self._set('background_data', value)

    def getBackgroundData(self):
        return self._get('background_data')

    def setFused(self, value):
        return self._set('fused', value)

    def getFused(self):
        return self._get('fused')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setInputCols(self, value):
        return self._set('input_cols', value)

    def getInputCols(self):
        return self._get('input_cols')

    def setModel(self, value):
        return self._set('model', value)

    def getModel(self):
        return self._get('model')

    def setNumSamples(self, value):
        return self._set('num_samples', value)

    def getNumSamples(self):
        return self._get('num_samples')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setTargetClasses(self, value):
        return self._set('target_classes', value)

    def getTargetClasses(self):
        return self._get('target_classes')

    def setTargetCol(self, value):
        return self._set('target_col', value)

    def getTargetCol(self):
        return self._get('target_col')


class TextSHAP(WrapperBase):
    """(ref ``TextSHAP.scala``) tokens as players; off tokens dropped. (wraps ``synapseml_tpu.explainers.shap.TextSHAP``)."""

    _target = 'synapseml_tpu.explainers.shap.TextSHAP'

    def setFused(self, value):
        return self._set('fused', value)

    def getFused(self):
        return self._get('fused')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setModel(self, value):
        return self._set('model', value)

    def getModel(self):
        return self._get('model')

    def setNumSamples(self, value):
        return self._set('num_samples', value)

    def getNumSamples(self):
        return self._get('num_samples')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setTargetClasses(self, value):
        return self._set('target_classes', value)

    def getTargetClasses(self):
        return self._get('target_classes')

    def setTargetCol(self, value):
        return self._set('target_col', value)

    def getTargetCol(self):
        return self._get('target_col')

    def setTokenCol(self, value):
        return self._set('token_col', value)

    def getTokenCol(self):
        return self._get('token_col')


class VectorSHAP(WrapperBase):
    """(ref ``VectorSHAP.scala``) feature-vector rows; off features are (wraps ``synapseml_tpu.explainers.shap.VectorSHAP``)."""

    _target = 'synapseml_tpu.explainers.shap.VectorSHAP'

    def setBackgroundData(self, value):
        return self._set('background_data', value)

    def getBackgroundData(self):
        return self._get('background_data')

    def setFused(self, value):
        return self._set('fused', value)

    def getFused(self):
        return self._get('fused')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setModel(self, value):
        return self._set('model', value)

    def getModel(self):
        return self._get('model')

    def setNumSamples(self, value):
        return self._set('num_samples', value)

    def getNumSamples(self):
        return self._get('num_samples')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setTargetClasses(self, value):
        return self._set('target_classes', value)

    def getTargetClasses(self):
        return self._get('target_classes')

    def setTargetCol(self, value):
        return self._set('target_col', value)

    def getTargetCol(self):
        return self._get('target_col')

