"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class HTTPTransformer(WrapperBase):
    """request col (HTTPRequest or None) -> response col (wraps ``synapseml_tpu.io.http.HTTPTransformer``)."""

    _target = 'synapseml_tpu.io.http.HTTPTransformer'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')


class SimpleHTTPTransformer(WrapperBase):
    """input parser -> HTTPTransformer -> output parser, with an errors column (wraps ``synapseml_tpu.io.http.SimpleHTTPTransformer``)."""

    _target = 'synapseml_tpu.io.http.SimpleHTTPTransformer'

    def setBackoffsMs(self, value):
        return self._set('backoffs_ms', value)

    def getBackoffsMs(self):
        return self._get('backoffs_ms')

    def setConcurrency(self, value):
        return self._set('concurrency', value)

    def getConcurrency(self):
        return self._get('concurrency')

    def setErrorCol(self, value):
        return self._set('error_col', value)

    def getErrorCol(self):
        return self._get('error_col')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setInputParser(self, value):
        return self._set('input_parser', value)

    def getInputParser(self):
        return self._get('input_parser')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setOutputParser(self, value):
        return self._set('output_parser', value)

    def getOutputParser(self):
        return self._get('output_parser')

    def setRetryPolicy(self, value):
        return self._set('retry_policy', value)

    def getRetryPolicy(self):
        return self._get('retry_policy')

    def setTimeoutS(self, value):
        return self._set('timeout_s', value)

    def getTimeoutS(self):
        return self._get('timeout_s')

