"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class VowpalWabbitContextualBandit(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.vw.contextual_bandit.VowpalWabbitContextualBandit``)."""

    _target = 'synapseml_tpu.vw.contextual_bandit.VowpalWabbitContextualBandit'

    def setBatchSize(self, value):
        return self._set('batch_size', value)

    def getBatchSize(self):
        return self._get('batch_size')

    def setChosenActionCol(self, value):
        return self._set('chosen_action_col', value)

    def getChosenActionCol(self):
        return self._get('chosen_action_col')

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setInteractions(self, value):
        return self._set('interactions', value)

    def getInteractions(self):
        return self._get('interactions')

    def setL1(self, value):
        return self._set('l1', value)

    def getL1(self):
        return self._get('l1')

    def setL2(self, value):
        return self._set('l2', value)

    def getL2(self):
        return self._get('l2')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setLearningRate(self, value):
        return self._set('learning_rate', value)

    def getLearningRate(self):
        return self._get('learning_rate')

    def setNumBits(self, value):
        return self._set('num_bits', value)

    def getNumBits(self):
        return self._get('num_bits')

    def setNumPasses(self, value):
        return self._set('num_passes', value)

    def getNumPasses(self):
        return self._get('num_passes')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setProbabilityCol(self, value):
        return self._set('probability_col', value)

    def getProbabilityCol(self):
        return self._get('probability_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setSharedCol(self, value):
        return self._set('shared_col', value)

    def getSharedCol(self):
        return self._get('shared_col')


class VowpalWabbitContextualBanditModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.vw.contextual_bandit.VowpalWabbitContextualBanditModel``)."""

    _target = 'synapseml_tpu.vw.contextual_bandit.VowpalWabbitContextualBanditModel'

    def setBatchSize(self, value):
        return self._set('batch_size', value)

    def getBatchSize(self):
        return self._get('batch_size')

    def setChosenActionCol(self, value):
        return self._set('chosen_action_col', value)

    def getChosenActionCol(self):
        return self._get('chosen_action_col')

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setInteractions(self, value):
        return self._set('interactions', value)

    def getInteractions(self):
        return self._get('interactions')

    def setL1(self, value):
        return self._set('l1', value)

    def getL1(self):
        return self._get('l1')

    def setL2(self, value):
        return self._set('l2', value)

    def getL2(self):
        return self._get('l2')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setLearningRate(self, value):
        return self._set('learning_rate', value)

    def getLearningRate(self):
        return self._get('learning_rate')

    def setModelWeights(self, value):
        return self._set('model_weights', value)

    def getModelWeights(self):
        return self._get('model_weights')

    def setNumBits(self, value):
        return self._set('num_bits', value)

    def getNumBits(self):
        return self._get('num_bits')

    def setNumPasses(self, value):
        return self._set('num_passes', value)

    def getNumPasses(self):
        return self._get('num_passes')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setProbabilityCol(self, value):
        return self._set('probability_col', value)

    def getProbabilityCol(self):
        return self._get('probability_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setSharedCol(self, value):
        return self._set('shared_col', value)

    def getSharedCol(self):
        return self._get('shared_col')


class VowpalWabbitDSJsonTransformer(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.vw.dsjson.VowpalWabbitDSJsonTransformer``)."""

    _target = 'synapseml_tpu.vw.dsjson.VowpalWabbitDSJsonTransformer'

    def setDsjsonCol(self, value):
        return self._set('dsjson_col', value)

    def getDsjsonCol(self):
        return self._get('dsjson_col')


class VowpalWabbitClassificationModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.vw.estimators.VowpalWabbitClassificationModel``)."""

    _target = 'synapseml_tpu.vw.estimators.VowpalWabbitClassificationModel'

    def setAdaptive(self, value):
        return self._set('adaptive', value)

    def getAdaptive(self):
        return self._get('adaptive')

    def setBatchSize(self, value):
        return self._set('batch_size', value)

    def getBatchSize(self):
        return self._get('batch_size')

    def setClasses(self, value):
        return self._set('classes', value)

    def getClasses(self):
        return self._get('classes')

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setInitialModel(self, value):
        return self._set('initial_model', value)

    def getInitialModel(self):
        return self._get('initial_model')

    def setL1(self, value):
        return self._set('l1', value)

    def getL1(self):
        return self._get('l1')

    def setL2(self, value):
        return self._set('l2', value)

    def getL2(self):
        return self._get('l2')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setLearningRate(self, value):
        return self._set('learning_rate', value)

    def getLearningRate(self):
        return self._get('learning_rate')

    def setModelWeights(self, value):
        return self._set('model_weights', value)

    def getModelWeights(self):
        return self._get('model_weights')

    def setNumBits(self, value):
        return self._set('num_bits', value)

    def getNumBits(self):
        return self._get('num_bits')

    def setNumPasses(self, value):
        return self._set('num_passes', value)

    def getNumPasses(self):
        return self._get('num_passes')

    def setPowerT(self, value):
        return self._set('power_t', value)

    def getPowerT(self):
        return self._get('power_t')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setProbabilityCol(self, value):
        return self._set('probability_col', value)

    def getProbabilityCol(self):
        return self._get('probability_col')

    def setRawPredictionCol(self, value):
        return self._set('raw_prediction_col', value)

    def getRawPredictionCol(self):
        return self._get('raw_prediction_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setWeightCol(self, value):
        return self._set('weight_col', value)

    def getWeightCol(self):
        return self._get('weight_col')


class VowpalWabbitClassifier(WrapperBase):
    """Binary classifier, logistic loss by default (reference (wraps ``synapseml_tpu.vw.estimators.VowpalWabbitClassifier``)."""

    _target = 'synapseml_tpu.vw.estimators.VowpalWabbitClassifier'

    def setAdaptive(self, value):
        return self._set('adaptive', value)

    def getAdaptive(self):
        return self._get('adaptive')

    def setBatchSize(self, value):
        return self._set('batch_size', value)

    def getBatchSize(self):
        return self._get('batch_size')

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setInitialModel(self, value):
        return self._set('initial_model', value)

    def getInitialModel(self):
        return self._get('initial_model')

    def setL1(self, value):
        return self._set('l1', value)

    def getL1(self):
        return self._get('l1')

    def setL2(self, value):
        return self._set('l2', value)

    def getL2(self):
        return self._get('l2')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setLearningRate(self, value):
        return self._set('learning_rate', value)

    def getLearningRate(self):
        return self._get('learning_rate')

    def setLossFunction(self, value):
        return self._set('loss_function', value)

    def getLossFunction(self):
        return self._get('loss_function')

    def setNumBits(self, value):
        return self._set('num_bits', value)

    def getNumBits(self):
        return self._get('num_bits')

    def setNumPasses(self, value):
        return self._set('num_passes', value)

    def getNumPasses(self):
        return self._get('num_passes')

    def setPowerT(self, value):
        return self._set('power_t', value)

    def getPowerT(self):
        return self._get('power_t')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setProbabilityCol(self, value):
        return self._set('probability_col', value)

    def getProbabilityCol(self):
        return self._get('probability_col')

    def setRawPredictionCol(self, value):
        return self._set('raw_prediction_col', value)

    def getRawPredictionCol(self):
        return self._get('raw_prediction_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setWeightCol(self, value):
        return self._set('weight_col', value)

    def getWeightCol(self):
        return self._get('weight_col')


class VowpalWabbitGeneric(WrapperBase):
    """Raw VW-text-line input mode (reference ``VowpalWabbitGeneric``). (wraps ``synapseml_tpu.vw.estimators.VowpalWabbitGeneric``)."""

    _target = 'synapseml_tpu.vw.estimators.VowpalWabbitGeneric'

    def setAdaptive(self, value):
        return self._set('adaptive', value)

    def getAdaptive(self):
        return self._get('adaptive')

    def setBatchSize(self, value):
        return self._set('batch_size', value)

    def getBatchSize(self):
        return self._get('batch_size')

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setInitialModel(self, value):
        return self._set('initial_model', value)

    def getInitialModel(self):
        return self._get('initial_model')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setL1(self, value):
        return self._set('l1', value)

    def getL1(self):
        return self._get('l1')

    def setL2(self, value):
        return self._set('l2', value)

    def getL2(self):
        return self._get('l2')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setLearningRate(self, value):
        return self._set('learning_rate', value)

    def getLearningRate(self):
        return self._get('learning_rate')

    def setLossFunction(self, value):
        return self._set('loss_function', value)

    def getLossFunction(self):
        return self._get('loss_function')

    def setNumBits(self, value):
        return self._set('num_bits', value)

    def getNumBits(self):
        return self._get('num_bits')

    def setNumPasses(self, value):
        return self._set('num_passes', value)

    def getNumPasses(self):
        return self._get('num_passes')

    def setPowerT(self, value):
        return self._set('power_t', value)

    def getPowerT(self):
        return self._get('power_t')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setWeightCol(self, value):
        return self._set('weight_col', value)

    def getWeightCol(self):
        return self._get('weight_col')


class VowpalWabbitGenericModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.vw.estimators.VowpalWabbitGenericModel``)."""

    _target = 'synapseml_tpu.vw.estimators.VowpalWabbitGenericModel'

    def setAdaptive(self, value):
        return self._set('adaptive', value)

    def getAdaptive(self):
        return self._get('adaptive')

    def setBatchSize(self, value):
        return self._set('batch_size', value)

    def getBatchSize(self):
        return self._get('batch_size')

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setInitialModel(self, value):
        return self._set('initial_model', value)

    def getInitialModel(self):
        return self._get('initial_model')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setL1(self, value):
        return self._set('l1', value)

    def getL1(self):
        return self._get('l1')

    def setL2(self, value):
        return self._set('l2', value)

    def getL2(self):
        return self._get('l2')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setLearningRate(self, value):
        return self._set('learning_rate', value)

    def getLearningRate(self):
        return self._get('learning_rate')

    def setLossFunction(self, value):
        return self._set('loss_function', value)

    def getLossFunction(self):
        return self._get('loss_function')

    def setModelWeights(self, value):
        return self._set('model_weights', value)

    def getModelWeights(self):
        return self._get('model_weights')

    def setNumBits(self, value):
        return self._set('num_bits', value)

    def getNumBits(self):
        return self._get('num_bits')

    def setNumPasses(self, value):
        return self._set('num_passes', value)

    def getNumPasses(self):
        return self._get('num_passes')

    def setPowerT(self, value):
        return self._set('power_t', value)

    def getPowerT(self):
        return self._get('power_t')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setWeightCol(self, value):
        return self._set('weight_col', value)

    def getWeightCol(self):
        return self._get('weight_col')


class VowpalWabbitProgressive(WrapperBase):
    """Progressive (streaming-eval) mode: fit() consumes rows IN ORDER, and (wraps ``synapseml_tpu.vw.estimators.VowpalWabbitProgressive``)."""

    _target = 'synapseml_tpu.vw.estimators.VowpalWabbitProgressive'

    def setAdaptive(self, value):
        return self._set('adaptive', value)

    def getAdaptive(self):
        return self._get('adaptive')

    def setBatchSize(self, value):
        return self._set('batch_size', value)

    def getBatchSize(self):
        return self._get('batch_size')

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setInitialModel(self, value):
        return self._set('initial_model', value)

    def getInitialModel(self):
        return self._get('initial_model')

    def setL1(self, value):
        return self._set('l1', value)

    def getL1(self):
        return self._get('l1')

    def setL2(self, value):
        return self._set('l2', value)

    def getL2(self):
        return self._get('l2')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setLearningRate(self, value):
        return self._set('learning_rate', value)

    def getLearningRate(self):
        return self._get('learning_rate')

    def setLossFunction(self, value):
        return self._set('loss_function', value)

    def getLossFunction(self):
        return self._get('loss_function')

    def setNumBits(self, value):
        return self._set('num_bits', value)

    def getNumBits(self):
        return self._get('num_bits')

    def setNumPasses(self, value):
        return self._set('num_passes', value)

    def getNumPasses(self):
        return self._get('num_passes')

    def setPowerT(self, value):
        return self._set('power_t', value)

    def getPowerT(self):
        return self._get('power_t')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setProgressiveCol(self, value):
        return self._set('progressive_col', value)

    def getProgressiveCol(self):
        return self._get('progressive_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setWeightCol(self, value):
        return self._set('weight_col', value)

    def getWeightCol(self):
        return self._get('weight_col')


class VowpalWabbitRegressionModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.vw.estimators.VowpalWabbitRegressionModel``)."""

    _target = 'synapseml_tpu.vw.estimators.VowpalWabbitRegressionModel'

    def setAdaptive(self, value):
        return self._set('adaptive', value)

    def getAdaptive(self):
        return self._get('adaptive')

    def setBatchSize(self, value):
        return self._set('batch_size', value)

    def getBatchSize(self):
        return self._get('batch_size')

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setInitialModel(self, value):
        return self._set('initial_model', value)

    def getInitialModel(self):
        return self._get('initial_model')

    def setL1(self, value):
        return self._set('l1', value)

    def getL1(self):
        return self._get('l1')

    def setL2(self, value):
        return self._set('l2', value)

    def getL2(self):
        return self._get('l2')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setLearningRate(self, value):
        return self._set('learning_rate', value)

    def getLearningRate(self):
        return self._get('learning_rate')

    def setModelWeights(self, value):
        return self._set('model_weights', value)

    def getModelWeights(self):
        return self._get('model_weights')

    def setNumBits(self, value):
        return self._set('num_bits', value)

    def getNumBits(self):
        return self._get('num_bits')

    def setNumPasses(self, value):
        return self._set('num_passes', value)

    def getNumPasses(self):
        return self._get('num_passes')

    def setPowerT(self, value):
        return self._set('power_t', value)

    def getPowerT(self):
        return self._get('power_t')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setWeightCol(self, value):
        return self._set('weight_col', value)

    def getWeightCol(self):
        return self._get('weight_col')


class VowpalWabbitRegressor(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.vw.estimators.VowpalWabbitRegressor``)."""

    _target = 'synapseml_tpu.vw.estimators.VowpalWabbitRegressor'

    def setAdaptive(self, value):
        return self._set('adaptive', value)

    def getAdaptive(self):
        return self._get('adaptive')

    def setBatchSize(self, value):
        return self._set('batch_size', value)

    def getBatchSize(self):
        return self._get('batch_size')

    def setFeaturesCol(self, value):
        return self._set('features_col', value)

    def getFeaturesCol(self):
        return self._get('features_col')

    def setInitialModel(self, value):
        return self._set('initial_model', value)

    def getInitialModel(self):
        return self._get('initial_model')

    def setL1(self, value):
        return self._set('l1', value)

    def getL1(self):
        return self._get('l1')

    def setL2(self, value):
        return self._set('l2', value)

    def getL2(self):
        return self._get('l2')

    def setLabelCol(self, value):
        return self._set('label_col', value)

    def getLabelCol(self):
        return self._get('label_col')

    def setLearningRate(self, value):
        return self._set('learning_rate', value)

    def getLearningRate(self):
        return self._get('learning_rate')

    def setLossFunction(self, value):
        return self._set('loss_function', value)

    def getLossFunction(self):
        return self._get('loss_function')

    def setNumBits(self, value):
        return self._set('num_bits', value)

    def getNumBits(self):
        return self._get('num_bits')

    def setNumPasses(self, value):
        return self._set('num_passes', value)

    def getNumPasses(self):
        return self._get('num_passes')

    def setPowerT(self, value):
        return self._set('power_t', value)

    def getPowerT(self):
        return self._get('power_t')

    def setPredictionCol(self, value):
        return self._set('prediction_col', value)

    def getPredictionCol(self):
        return self._get('prediction_col')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setWeightCol(self, value):
        return self._set('weight_col', value)

    def getWeightCol(self):
        return self._get('weight_col')


class VowpalWabbitFeaturizer(WrapperBase):
    """Hash input columns into one padded-sparse feature column. (wraps ``synapseml_tpu.vw.featurizer.VowpalWabbitFeaturizer``)."""

    _target = 'synapseml_tpu.vw.featurizer.VowpalWabbitFeaturizer'

    def setInputCols(self, value):
        return self._set('input_cols', value)

    def getInputCols(self):
        return self._get('input_cols')

    def setMaxNnz(self, value):
        return self._set('max_nnz', value)

    def getMaxNnz(self):
        return self._get('max_nnz')

    def setNumBits(self, value):
        return self._set('num_bits', value)

    def getNumBits(self):
        return self._get('num_bits')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setStringSplitCols(self, value):
        return self._set('string_split_cols', value)

    def getStringSplitCols(self):
        return self._get('string_split_cols')

    def setSumCollisions(self, value):
        return self._set('sum_collisions', value)

    def getSumCollisions(self):
        return self._get('sum_collisions')


class VowpalWabbitCSETransformer(WrapperBase):
    """Counterfactual selection evaluation: aggregates logged bandit rows into (wraps ``synapseml_tpu.vw.policyeval.VowpalWabbitCSETransformer``)."""

    _target = 'synapseml_tpu.vw.policyeval.VowpalWabbitCSETransformer'

    def setLoggedProbabilityCol(self, value):
        return self._set('logged_probability_col', value)

    def getLoggedProbabilityCol(self):
        return self._get('logged_probability_col')

    def setMaxImportanceWeight(self, value):
        return self._set('max_importance_weight', value)

    def getMaxImportanceWeight(self):
        return self._get('max_importance_weight')

    def setMinImportanceWeight(self, value):
        return self._set('min_importance_weight', value)

    def getMinImportanceWeight(self):
        return self._get('min_importance_weight')

    def setRewardCol(self, value):
        return self._set('reward_col', value)

    def getRewardCol(self):
        return self._get('reward_col')

    def setTargetProbabilityCol(self, value):
        return self._set('target_probability_col', value)

    def getTargetProbabilityCol(self):
        return self._get('target_probability_col')

