"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class CleanMissingData(WrapperBase):
    """Impute NaNs with mean/median/custom (ref ``CleanMissingData.scala:51``). (wraps ``synapseml_tpu.featurize.clean.CleanMissingData``)."""

    _target = 'synapseml_tpu.featurize.clean.CleanMissingData'

    def setCleaningMode(self, value):
        return self._set('cleaning_mode', value)

    def getCleaningMode(self):
        return self._get('cleaning_mode')

    def setCustomValue(self, value):
        return self._set('custom_value', value)

    def getCustomValue(self):
        return self._get('custom_value')

    def setInputCols(self, value):
        return self._set('input_cols', value)

    def getInputCols(self):
        return self._get('input_cols')

    def setOutputCols(self, value):
        return self._set('output_cols', value)

    def getOutputCols(self):
        return self._get('output_cols')


class CleanMissingDataModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.featurize.clean.CleanMissingDataModel``)."""

    _target = 'synapseml_tpu.featurize.clean.CleanMissingDataModel'

    def setFillValues(self, value):
        return self._set('fill_values', value)

    def getFillValues(self):
        return self._get('fill_values')

    def setInputCols(self, value):
        return self._set('input_cols', value)

    def getInputCols(self):
        return self._get('input_cols')

    def setOutputCols(self, value):
        return self._set('output_cols', value)

    def getOutputCols(self):
        return self._get('output_cols')


class DataConversion(WrapperBase):
    """Cast columns to a named type (ref ``featurize/DataConversion.scala``); (wraps ``synapseml_tpu.featurize.clean.DataConversion``)."""

    _target = 'synapseml_tpu.featurize.clean.DataConversion'

    def setCols(self, value):
        return self._set('cols', value)

    def getCols(self):
        return self._get('cols')

    def setConvertTo(self, value):
        return self._set('convert_to', value)

    def getConvertTo(self):
        return self._get('convert_to')

    def setDateTimeFormat(self, value):
        return self._set('date_time_format', value)

    def getDateTimeFormat(self):
        return self._get('date_time_format')


class Featurize(WrapperBase):
    """Auto-featurization estimator (ref ``Featurize.scala:35``). (wraps ``synapseml_tpu.featurize.featurize.Featurize``)."""

    _target = 'synapseml_tpu.featurize.featurize.Featurize'

    def setImputeMissing(self, value):
        return self._set('impute_missing', value)

    def getImputeMissing(self):
        return self._get('impute_missing')

    def setInputCols(self, value):
        return self._set('input_cols', value)

    def getInputCols(self):
        return self._get('input_cols')

    def setMaxOneHotCardinality(self, value):
        return self._set('max_one_hot_cardinality', value)

    def getMaxOneHotCardinality(self):
        return self._get('max_one_hot_cardinality')

    def setNumFeatures(self, value):
        return self._set('num_features', value)

    def getNumFeatures(self):
        return self._get('num_features')

    def setOneHotEncodeCategoricals(self, value):
        return self._set('one_hot_encode_categoricals', value)

    def getOneHotEncodeCategoricals(self):
        return self._get('one_hot_encode_categoricals')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')


class FeaturizeModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.featurize.featurize.FeaturizeModel``)."""

    _target = 'synapseml_tpu.featurize.featurize.FeaturizeModel'

    def setInputCols(self, value):
        return self._set('input_cols', value)

    def getInputCols(self):
        return self._get('input_cols')

    def setNumFeatures(self, value):
        return self._set('num_features', value)

    def getNumFeatures(self):
        return self._get('num_features')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setPlan(self, value):
        return self._set('plan', value)

    def getPlan(self):
        return self._get('plan')


class CountSelector(WrapperBase):
    """Drop always-zero feature slots (ref ``featurize/CountSelector.scala`` — (wraps ``synapseml_tpu.featurize.indexers.CountSelector``)."""

    _target = 'synapseml_tpu.featurize.indexers.CountSelector'

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')


class CountSelectorModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.featurize.indexers.CountSelectorModel``)."""

    _target = 'synapseml_tpu.featurize.indexers.CountSelectorModel'

    def setIndices(self, value):
        return self._set('indices', value)

    def getIndices(self):
        return self._get('indices')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')


class IndexToValue(WrapperBase):
    """Inverse of ValueIndexerModel (ref ``featurize/IndexToValue.scala``): (wraps ``synapseml_tpu.featurize.indexers.IndexToValue``)."""

    _target = 'synapseml_tpu.featurize.indexers.IndexToValue'

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setLevels(self, value):
        return self._set('levels', value)

    def getLevels(self):
        return self._get('levels')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')


class ValueIndexer(WrapperBase):
    """Learn distinct levels -> contiguous indices (ref ``ValueIndexer.scala:57``). (wraps ``synapseml_tpu.featurize.indexers.ValueIndexer``)."""

    _target = 'synapseml_tpu.featurize.indexers.ValueIndexer'

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setUnknownIndex(self, value):
        return self._set('unknown_index', value)

    def getUnknownIndex(self):
        return self._get('unknown_index')


class ValueIndexerModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.featurize.indexers.ValueIndexerModel``)."""

    _target = 'synapseml_tpu.featurize.indexers.ValueIndexerModel'

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setLevels(self, value):
        return self._set('levels', value)

    def getLevels(self):
        return self._get('levels')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setUnknownIndex(self, value):
        return self._set('unknown_index', value)

    def getUnknownIndex(self):
        return self._get('unknown_index')


class MultiNGram(WrapperBase):
    """Token lists -> concatenated ngrams of several lengths (wraps ``synapseml_tpu.featurize.text.MultiNGram``)."""

    _target = 'synapseml_tpu.featurize.text.MultiNGram'

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setLengths(self, value):
        return self._set('lengths', value)

    def getLengths(self):
        return self._get('lengths')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')


class PageSplitter(WrapperBase):
    """Split text into page strings within [min,max] length, preferring word (wraps ``synapseml_tpu.featurize.text.PageSplitter``)."""

    _target = 'synapseml_tpu.featurize.text.PageSplitter'

    def setBoundaryRegex(self, value):
        return self._set('boundary_regex', value)

    def getBoundaryRegex(self):
        return self._get('boundary_regex')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setMaximumPageLength(self, value):
        return self._set('maximum_page_length', value)

    def getMaximumPageLength(self):
        return self._get('maximum_page_length')

    def setMinimumPageLength(self, value):
        return self._set('minimum_page_length', value)

    def getMinimumPageLength(self):
        return self._get('minimum_page_length')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')


class TextFeaturizer(WrapperBase):
    """(ref ``TextFeaturizer.scala:193``) (wraps ``synapseml_tpu.featurize.text.TextFeaturizer``)."""

    _target = 'synapseml_tpu.featurize.text.TextFeaturizer'

    def setBinary(self, value):
        return self._set('binary', value)

    def getBinary(self):
        return self._get('binary')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setMinDocFreq(self, value):
        return self._set('min_doc_freq', value)

    def getMinDocFreq(self):
        return self._get('min_doc_freq')

    def setNGramLength(self, value):
        return self._set('n_gram_length', value)

    def getNGramLength(self):
        return self._get('n_gram_length')

    def setNumFeatures(self, value):
        return self._set('num_features', value)

    def getNumFeatures(self):
        return self._get('num_features')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setToLowerCase(self, value):
        return self._set('to_lower_case', value)

    def getToLowerCase(self):
        return self._get('to_lower_case')

    def setUseIdf(self, value):
        return self._set('use_idf', value)

    def getUseIdf(self):
        return self._get('use_idf')


class TextFeaturizerModel(WrapperBase):
    """A fitted Transformer (SparkML Model[M]). (wraps ``synapseml_tpu.featurize.text.TextFeaturizerModel``)."""

    _target = 'synapseml_tpu.featurize.text.TextFeaturizerModel'

    def setBinary(self, value):
        return self._set('binary', value)

    def getBinary(self):
        return self._get('binary')

    def setIdf(self, value):
        return self._set('idf', value)

    def getIdf(self):
        return self._get('idf')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setNGramLength(self, value):
        return self._set('n_gram_length', value)

    def getNGramLength(self):
        return self._get('n_gram_length')

    def setNumFeatures(self, value):
        return self._set('num_features', value)

    def getNumFeatures(self):
        return self._get('num_features')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setToLowerCase(self, value):
        return self._set('to_lower_case', value)

    def getToLowerCase(self):
        return self._get('to_lower_case')

