"""Generated pyspark-style wrappers — do not edit.

Regenerate with ``python -m synapseml_tpu.codegen`` (emit_wrappers). The
reference's codegen (``Wrappable.scala:56-389``) emits the same surface from
Scala stages; here it is emitted from the native param registry.
"""

from ._base import WrapperBase


class HuggingFaceCausalLM(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.hf.causal_lm.HuggingFaceCausalLM``)."""

    _target = 'synapseml_tpu.hf.causal_lm.HuggingFaceCausalLM'

    def setBatchSize(self, value):
        return self._set('batch_size', value)

    def getBatchSize(self):
        return self._get('batch_size')

    def setDecodeSlots(self, value):
        return self._set('decode_slots', value)

    def getDecodeSlots(self):
        return self._get('decode_slots')

    def setDoSample(self, value):
        return self._set('do_sample', value)

    def getDoSample(self):
        return self._get('do_sample')

    def setDraftTokens(self, value):
        return self._set('draft_tokens', value)

    def getDraftTokens(self):
        return self._get('draft_tokens')

    def setDrafterRef(self, value):
        return self._set('drafter_ref', value)

    def getDrafterRef(self):
        return self._get('drafter_ref')

    def setEngine(self, value):
        return self._set('engine', value)

    def getEngine(self):
        return self._get('engine')

    def setEosId(self, value):
        return self._set('eos_id', value)

    def getEosId(self):
        return self._get('eos_id')

    def setGenerationParamsCol(self, value):
        return self._set('generation_params_col', value)

    def getGenerationParamsCol(self):
        return self._get('generation_params_col')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setKvBlockLen(self, value):
        return self._set('kv_block_len', value)

    def getKvBlockLen(self):
        return self._get('kv_block_len')

    def setKvBlocks(self, value):
        return self._set('kv_blocks', value)

    def getKvBlocks(self):
        return self._get('kv_blocks')

    def setMaxNewTokens(self, value):
        return self._set('max_new_tokens', value)

    def getMaxNewTokens(self):
        return self._get('max_new_tokens')

    def setMeshConfig(self, value):
        return self._set('mesh_config', value)

    def getMeshConfig(self):
        return self._get('mesh_config')

    def setMessagesCol(self, value):
        return self._set('messages_col', value)

    def getMessagesCol(self):
        return self._get('messages_col')

    def setModelName(self, value):
        return self._set('model_name', value)

    def getModelName(self):
        return self._get('model_name')

    def setModelParams(self, value):
        return self._set('model_params', value)

    def getModelParams(self):
        return self._get('model_params')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setPartitionRules(self, value):
        return self._set('partition_rules', value)

    def getPartitionRules(self):
        return self._get('partition_rules')

    def setPrefixCache(self, value):
        return self._set('prefix_cache', value)

    def getPrefixCache(self):
        return self._get('prefix_cache')

    def setPromptBucket(self, value):
        return self._set('prompt_bucket', value)

    def getPromptBucket(self):
        return self._get('prompt_bucket')

    def setSeed(self, value):
        return self._set('seed', value)

    def getSeed(self):
        return self._get('seed')

    def setTemperature(self, value):
        return self._set('temperature', value)

    def getTemperature(self):
        return self._get('temperature')

    def setTokenizer(self, value):
        return self._set('tokenizer', value)

    def getTokenizer(self):
        return self._get('tokenizer')

    def setTopK(self, value):
        return self._set('top_k', value)

    def getTopK(self):
        return self._get('top_k')

    def setTopP(self, value):
        return self._set('top_p', value)

    def getTopP(self):
        return self._get('top_p')


class HuggingFaceSentenceEmbedder(WrapperBase):
    """Base of every stage; persists via metadata.json + out-of-band complex params. (wraps ``synapseml_tpu.hf.embedder.HuggingFaceSentenceEmbedder``)."""

    _target = 'synapseml_tpu.hf.embedder.HuggingFaceSentenceEmbedder'

    def setBatchSize(self, value):
        return self._set('batch_size', value)

    def getBatchSize(self):
        return self._get('batch_size')

    def setInputCol(self, value):
        return self._set('input_col', value)

    def getInputCol(self):
        return self._get('input_col')

    def setMaxTokenLen(self, value):
        return self._set('max_token_len', value)

    def getMaxTokenLen(self):
        return self._get('max_token_len')

    def setMeshConfig(self, value):
        return self._set('mesh_config', value)

    def getMeshConfig(self):
        return self._get('mesh_config')

    def setModelName(self, value):
        return self._set('model_name', value)

    def getModelName(self):
        return self._get('model_name')

    def setModelParams(self, value):
        return self._set('model_params', value)

    def getModelParams(self):
        return self._get('model_params')

    def setNormalize(self, value):
        return self._set('normalize', value)

    def getNormalize(self):
        return self._get('normalize')

    def setOutputCol(self, value):
        return self._set('output_col', value)

    def getOutputCol(self):
        return self._get('output_col')

    def setPooling(self, value):
        return self._set('pooling', value)

    def getPooling(self):
        return self._get('pooling')

    def setTokenizer(self, value):
        return self._set('tokenizer', value)

    def getTokenizer(self):
        return self._get('tokenizer')

