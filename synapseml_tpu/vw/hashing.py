"""MurmurHash3 (x86 32-bit) — VW's feature hash, pure Python with caching.

Reference: the JNI binding ``VowpalWabbitMurmur`` used by
``VowpalWabbitMurmurWithPrefix.scala`` (namespace-prefixed feature hashing).
This matches VW's uniform hash (murmur3_32 of the UTF-8 name, seeded by the
namespace hash). Feature names repeat heavily across rows, so an LRU cache
makes the pure-Python path fast; a C implementation lands via
:mod:`synapseml_tpu.native` when built.
"""

from __future__ import annotations

import functools

__all__ = ["murmur3_32", "hash_feature", "namespace_seed"]

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_MASK = 0xFFFFFFFF


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _MASK


@functools.lru_cache(maxsize=1 << 20)
def murmur3_32(data: bytes, seed: int = 0) -> int:
    h = seed & _MASK
    n = len(data)
    nblocks = n // 4
    for i in range(nblocks):
        k = int.from_bytes(data[4 * i : 4 * i + 4], "little")
        k = (k * _C1) & _MASK
        k = _rotl(k, 15)
        k = (k * _C2) & _MASK
        h ^= k
        h = _rotl(h, 13)
        h = (h * 5 + 0xE6546B64) & _MASK
    tail = data[nblocks * 4 :]
    k = 0
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * _C1) & _MASK
        k = _rotl(k, 15)
        k = (k * _C2) & _MASK
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _MASK
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _MASK
    h ^= h >> 16
    return h


@functools.lru_cache(maxsize=1 << 10)
def namespace_seed(namespace: str) -> int:
    """VW hashes the namespace name to seed its features' hashes."""
    return murmur3_32(namespace.encode("utf-8"), 0)


@functools.lru_cache(maxsize=1 << 20)
def hash_feature(name: str, namespace: str = "", num_bits: int = 18) -> int:
    return murmur3_32(name.encode("utf-8"), namespace_seed(namespace)) & ((1 << num_bits) - 1)


def hash_features_batch(names, namespace: str = "", num_bits: int = 18):
    """Vectorized feature hashing: the C++ batch kernel when built
    (:mod:`synapseml_tpu.native`), else the cached Python path."""
    from .. import native

    out = native.murmur3_batch(list(names), seed=namespace_seed(namespace),
                               num_bits=num_bits)
    if out is not None:
        return out
    import numpy as np

    return np.asarray([hash_feature(n, namespace, num_bits) for n in names],
                      dtype=np.uint32)
