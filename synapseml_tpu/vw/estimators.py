"""VowpalWabbit estimators: Classifier / Regressor / Generic.

Reference: ``VowpalWabbitClassifier.scala:25``, ``VowpalWabbitRegressor.scala``,
``VowpalWabbitGeneric.scala:19-131`` and the shared arg-builder base
(``VowpalWabbitBase.scala:36-218``). The reference's ``passThroughArgs`` VW
command line maps onto explicit params here; ``VowpalWabbitGeneric`` keeps the
raw VW text-format input mode (it parses ``label | ns feature:value ...``
lines itself instead of handing them to libvw).
"""

from __future__ import annotations

import re

import numpy as np

from ..core import DataFrame, Estimator, Model
from ..core.params import ComplexParam, Param, TypeConverters
from .hashing import hash_feature
from .featurizer import pack_sparse
from .learner import LinearConfig, linear_predict, train_linear


def _stable_sigmoid(raw: np.ndarray) -> np.ndarray:
    """Overflow-safe logistic link (the naive form overflows at |raw| > ~88)."""
    e = np.exp(-np.abs(raw))
    return np.where(raw >= 0, 1.0 / (1.0 + e), e / (1.0 + e))


__all__ = [
    "VowpalWabbitClassifier", "VowpalWabbitClassificationModel",
    "VowpalWabbitRegressor", "VowpalWabbitRegressionModel",
    "VowpalWabbitGeneric", "VowpalWabbitGenericModel",
    "VowpalWabbitProgressive",
]


class _VWBaseParams:
    features_col = Param("features_col", "padded-sparse feature column prefix "
                         "(expects <col>_indices / <col>_values from the featurizer)",
                         default="features")
    label_col = Param("label_col", "label column", default="label")
    weight_col = Param("weight_col", "importance weight column", default=None)
    prediction_col = Param("prediction_col", "output column", default="prediction")
    num_bits = Param("num_bits", "hash space = 2^bits (VW -b)", default=18,
                     converter=TypeConverters.to_int)
    num_passes = Param("num_passes", "passes over the data (VW --passes)", default=1,
                       converter=TypeConverters.to_int)
    learning_rate = Param("learning_rate", "initial learning rate (VW -l)", default=0.5,
                          converter=TypeConverters.to_float)
    power_t = Param("power_t", "lr decay exponent (VW --power_t)", default=0.5,
                    converter=TypeConverters.to_float)
    l1 = Param("l1", "L1 regularization (VW --l1)", default=0.0,
               converter=TypeConverters.to_float)
    l2 = Param("l2", "L2 regularization (VW --l2)", default=0.0,
               converter=TypeConverters.to_float)
    adaptive = Param("adaptive", "AdaGrad-adaptive updates (VW default on)",
                     default=True, converter=TypeConverters.to_bool)
    batch_size = Param("batch_size", "TPU minibatch size per update (no VW analog: "
                       "the online loop is batched for the MXU)", default=256,
                       converter=TypeConverters.to_int)
    seed = Param("seed", "shuffle seed", default=0, converter=TypeConverters.to_int)
    initial_model = ComplexParam("initial_model", "warm-start weight vector "
                                 "(reference initialModel bytes param)", default=None)

    def _sparse(self, df: DataFrame):
        fc = self.get("features_col")
        self.require_columns(df, f"{fc}_indices", f"{fc}_values")
        idx = df.collect_column(f"{fc}_indices")
        val = df.collect_column(f"{fc}_values")
        return np.asarray(idx, np.int32), np.asarray(val, np.float32)

    def _config(self, loss: str) -> LinearConfig:
        return LinearConfig(
            num_bits=self.get("num_bits"), loss=loss,
            learning_rate=self.get("learning_rate"), power_t=self.get("power_t"),
            l1=self.get("l1"), l2=self.get("l2"),
            num_passes=self.get("num_passes"), batch_size=self.get("batch_size"),
            adaptive=self.get("adaptive"), seed=self.get("seed"))

    def _weights_arr(self, df: DataFrame):
        wc = self.get("weight_col")
        if not wc:
            return None
        self.require_columns(df, wc)
        return np.asarray(df.collect_column(wc), np.float32)


class _VWModelBase(Model, _VWBaseParams):
    model_weights = ComplexParam("model_weights", "trained weight vector (2^bits,)")

    def get_performance_statistics(self) -> dict:
        w = self.get("model_weights")
        return {"num_weights": int((w != 0).sum()), "dim": int(w.shape[0]),
                "weight_norm": float(np.linalg.norm(w))}

    def _raw_scores(self, df: DataFrame) -> np.ndarray:
        import jax.numpy as jnp

        idx, val = self._sparse(df)
        w = jnp.asarray(self.get("model_weights"))
        return np.asarray(linear_predict(w, jnp.asarray(idx), jnp.asarray(val)))


class VowpalWabbitClassifier(Estimator, _VWBaseParams):
    """Binary classifier, logistic loss by default (reference
    ``VowpalWabbitClassifier.scala:25`` forces ``--loss_function logistic``)."""

    feature_name = "vw"

    # the reference forces --loss_function logistic for the classifier
    # (VowpalWabbitClassifier.scala:25); the probability column is sigmoid(margin),
    # which is only calibrated for logistic loss, so other losses are rejected
    loss_function = Param("loss_function", "logistic", default="logistic",
                          validator=lambda v: v == "logistic")
    probability_col = Param("probability_col", "probability output column",
                            default="probability")
    raw_prediction_col = Param("raw_prediction_col", "margin output column",
                               default="rawPrediction")

    def _fit(self, df: DataFrame) -> "VowpalWabbitClassificationModel":
        idx, val = self._sparse(df)
        self.require_columns(df, self.get("label_col"))
        y_raw = np.asarray(df.collect_column(self.get("label_col")))
        classes = np.unique(y_raw)
        if len(classes) != 2:
            raise ValueError(f"binary classifier needs 2 classes, got {len(classes)}")
        y = np.where(y_raw == classes[1], 1.0, -1.0).astype(np.float32)
        w = train_linear(idx, val, y, self._config(self.get("loss_function")),
                         weights=self._weights_arr(df),
                         initial_weights=self.get("initial_model"))
        model = VowpalWabbitClassificationModel(model_weights=w, classes=classes)
        model.set(**{k: v for k, v in self._param_values.items() if model.has_param(k)})
        return model


class VowpalWabbitClassificationModel(_VWModelBase):
    feature_name = "vw"

    classes = ComplexParam("classes", "label values: [negative, positive]")
    probability_col = Param("probability_col", "probability output column",
                            default="probability")
    raw_prediction_col = Param("raw_prediction_col", "margin output column",
                               default="rawPrediction")

    def _transform(self, df: DataFrame) -> DataFrame:
        raw = self._raw_scores(df)
        prob = _stable_sigmoid(raw)
        classes = np.asarray(self.get("classes"))
        pred = classes[(prob >= 0.5).astype(int)]
        return (df.with_column(self.get("raw_prediction_col"), raw)
                  .with_column(self.get("probability_col"), prob)
                  .with_column(self.get("prediction_col"), pred))


class VowpalWabbitRegressor(Estimator, _VWBaseParams):
    feature_name = "vw"

    loss_function = Param("loss_function", "squared | quantile", default="squared")

    def _fit(self, df: DataFrame) -> "VowpalWabbitRegressionModel":
        idx, val = self._sparse(df)
        self.require_columns(df, self.get("label_col"))
        y = np.asarray(df.collect_column(self.get("label_col")), np.float32)
        w = train_linear(idx, val, y, self._config(self.get("loss_function")),
                         weights=self._weights_arr(df),
                         initial_weights=self.get("initial_model"))
        model = VowpalWabbitRegressionModel(model_weights=w)
        model.set(**{k: v for k, v in self._param_values.items() if model.has_param(k)})
        return model


class VowpalWabbitRegressionModel(_VWModelBase):
    feature_name = "vw"

    def _transform(self, df: DataFrame) -> DataFrame:
        return df.with_column(self.get("prediction_col"), self._raw_scores(df))


# ---------------- generic (VW text format) ----------------

_FEAT_RE = re.compile(r"([^\s:|]+)(?::([-+0-9.eE]+))?")


def parse_vw_line(line: str, num_bits: int):
    """Parse one VW text-format example: ``label [weight] | ns f:v f ... |ns2 ...``
    (the input mode of ``VowpalWabbitGeneric.scala:19-131``)."""
    head, _, rest = line.partition("|")
    head = head.strip().split()
    label = float(head[0]) if head else 0.0
    weight = float(head[1]) if len(head) > 1 else 1.0
    feats: list[tuple[int, float]] = []
    for section in rest.split("|"):
        if not section.strip():
            continue
        # VW: a namespace is flush against the bar ("|ns f"); a space after the
        # bar ("| f") means default namespace. split('|') preserves the leading
        # space, so inspect it before tokenizing.
        has_ns = not section[0].isspace()
        toks = section.split()
        ns, ns_scale = "", 1.0
        if has_ns:
            ns_tok, toks = toks[0], toks[1:]
            ns, _, scale_s = ns_tok.partition(":")
            if scale_s:
                ns_scale = float(scale_s)
        for tok in toks:
            m = _FEAT_RE.fullmatch(tok)
            if not m:
                continue
            name, v = m.group(1), m.group(2)
            feats.append((hash_feature(name, ns, num_bits),
                          (float(v) if v else 1.0) * ns_scale))
    return label, weight, feats


class VowpalWabbitGeneric(Estimator, _VWBaseParams):
    """Raw VW-text-line input mode (reference ``VowpalWabbitGeneric``)."""

    feature_name = "vw"

    input_col = Param("input_col", "column of VW text-format example lines",
                      default="input")
    loss_function = Param("loss_function", "squared | logistic | hinge | quantile",
                          default="squared")

    def _fit(self, df: DataFrame) -> "VowpalWabbitGenericModel":
        self.require_columns(df, self.get("input_col"))
        bits = self.get("num_bits")
        parsed = [parse_vw_line(str(l), bits) for l in df.collect_column(self.get("input_col"))]
        labels = np.asarray([p[0] for p in parsed], np.float32)
        weights = np.asarray([p[1] for p in parsed], np.float32)
        idx, val = pack_sparse([p[2] for p in parsed])
        if self.get("loss_function") == "logistic":
            labels = np.where(labels > 0, 1.0, -1.0).astype(np.float32)
        w = train_linear(idx, val, labels, self._config(self.get("loss_function")),
                         weights=weights, initial_weights=self.get("initial_model"))
        model = VowpalWabbitGenericModel(model_weights=w)
        model.set(**{k: v for k, v in self._param_values.items() if model.has_param(k)})
        return model


class VowpalWabbitGenericModel(_VWModelBase):
    feature_name = "vw"

    input_col = Param("input_col", "column of VW text-format example lines",
                      default="input")
    loss_function = Param("loss_function", "squared | logistic | hinge | quantile",
                          default="squared")

    def _transform(self, df: DataFrame) -> DataFrame:
        import jax.numpy as jnp

        self.require_columns(df, self.get("input_col"))
        bits = self.get("num_bits")
        parsed = [parse_vw_line(str(l), bits) for l in df.collect_column(self.get("input_col"))]
        idx, val = pack_sparse([p[2] for p in parsed])
        w = jnp.asarray(self.get("model_weights"))
        raw = np.asarray(linear_predict(w, jnp.asarray(idx), jnp.asarray(val)))
        if self.get("loss_function") == "logistic":
            raw = _stable_sigmoid(raw)
        return df.with_column(self.get("prediction_col"), raw)


class VowpalWabbitProgressive(Estimator, _VWBaseParams):
    """Progressive (streaming-eval) mode: fit() consumes rows IN ORDER, and
    the returned model's training trace carries each row's one-step-ahead
    prediction — the model's output for a row BEFORE learning from it
    (reference ``VowpalWabbitBaseProgressive.scala``). ``transform_progressive``
    does both in one shot, appending the progressive prediction column.

    ``batch_size=1`` reproduces VW's strictly-online updates; larger batches
    trade per-row fidelity for MXU throughput (rows inside a batch share the
    pre-batch weights)."""

    feature_name = "vw"

    loss_function = Param("loss_function", "squared | logistic | hinge | quantile",
                          default="squared")
    progressive_col = Param("progressive_col", "one-step-ahead prediction column",
                            default="progressive_prediction")

    def transform_progressive(self, df: DataFrame) -> tuple[DataFrame, "VowpalWabbitRegressionModel"]:
        """(df + progressive column, trained model)."""
        from .learner import train_linear_progressive

        idx, val = self._sparse(df)
        self.require_columns(df, self.get("label_col"))
        labels = np.asarray(df.collect_column(self.get("label_col")), np.float32)
        if self.get("loss_function") == "logistic":
            labels = np.where(labels > 0, 1.0, -1.0).astype(np.float32)
        logistic = self.get("loss_function") == "logistic"
        w, preds = train_linear_progressive(
            idx, val, labels, self._config(self.get("loss_function")),
            weights=self._weights_arr(df),
            initial_weights=self.get("initial_model"))
        if logistic:
            # progressive outputs are probabilities for logistic loss
            # (matching VowpalWabbitGenericModel's link function)
            preds = _stable_sigmoid(preds)
        offsets = np.cumsum([0] + [len(next(iter(p.values()))) for p in df.partitions])
        parts = []
        for i, p in enumerate(df.partitions):
            q = dict(p)
            q[self.get("progressive_col")] = preds[offsets[i]:offsets[i + 1]]
            parts.append(q)
        model_cls = (VowpalWabbitClassificationModel if logistic
                     else VowpalWabbitRegressionModel)
        model = model_cls(model_weights=w)
        if logistic:
            orig = np.unique(np.asarray(
                df.collect_column(self.get("label_col"))))
            model.set(classes=orig if len(orig) == 2 else np.asarray([0.0, 1.0]))
        model.set(**{k: v for k, v in self._param_values.items()
                     if model.has_param(k)})
        return DataFrame(parts), model

    def _fit(self, df: DataFrame):
        _, model = self.transform_progressive(df)
        return model
