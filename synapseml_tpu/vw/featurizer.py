"""VowpalWabbitFeaturizer — DataFrame columns → hashed sparse features.

Reference: ``vw/.../VowpalWabbitFeaturizer.scala:25-230`` + the per-type
featurizers in ``featurizer/*.scala`` (Numeric, String, StringSplit, Map,
Seq/Vector, Boolean) and namespace-prefixed murmur hashing
(``VowpalWabbitMurmurWithPrefix.scala``).

Output is the TPU-friendly padded-sparse layout: per row a fixed-width
``(indices int32[max_nnz], values float32[max_nnz])`` pair (padding has
value 0, which is a no-op for linear scores) — static shapes for jit.
"""

from __future__ import annotations

import numbers

import numpy as np

from ..core import DataFrame, Transformer
from ..core.params import Param, TypeConverters
from .hashing import hash_feature

__all__ = ["VowpalWabbitFeaturizer", "pack_sparse"]


def pack_sparse(rows: list[list[tuple[int, float]]], max_nnz: int | None = None):
    """Ragged (index, value) rows → padded (N, max_nnz) int32/float32 pair."""
    width = max_nnz or max((len(r) for r in rows), default=1)
    width = max(width, 1)
    idx = np.zeros((len(rows), width), np.int32)
    val = np.zeros((len(rows), width), np.float32)
    for i, r in enumerate(rows):
        r = r[:width]
        for j, (k, v) in enumerate(r):
            idx[i, j] = k
            val[i, j] = v
    return idx, val


class VowpalWabbitFeaturizer(Transformer):
    """Hash input columns into one padded-sparse feature column.

    Column handling mirrors the reference featurizer dispatch:
      * numeric → feature ``hash(colname)`` with the numeric value
      * bool → hash(colname) with 1.0 when true
      * str → categorical one-hot: ``hash(colname + '=' + value) -> 1.0``
        (``StringFeaturizer.scala``)
      * str with ``string_split_cols`` → one feature per whitespace token
      * dict → ``hash(colname + '.' + key)`` numeric, or categorical for str values
      * list/tuple/ndarray of numbers → ``hash(colname + '_' + i)`` per slot
    """

    feature_name = "vw"

    input_cols = Param("input_cols", "columns to hash", default=None,
                       converter=TypeConverters.to_list)
    output_col = Param("output_col", "output struct column prefix; emits "
                       "<out>_indices and <out>_values", default="features")
    num_bits = Param("num_bits", "hash space = 2^num_bits (VW -b)", default=18,
                     converter=TypeConverters.to_int)
    string_split_cols = Param("string_split_cols", "string columns tokenized on "
                              "whitespace (StringSplitFeaturizer)", default=(),
                              converter=TypeConverters.to_list)
    max_nnz = Param("max_nnz", "pad/truncate row features to this width "
                    "(None = widest row)", default=None)
    sum_collisions = Param("sum_collisions", "sum colliding feature values "
                           "(reference sumCollisions)", default=True,
                           converter=TypeConverters.to_bool)

    def _featurize_value(self, col: str, v, bits: int, split: bool) -> list[tuple[int, float]]:
        if v is None:
            return []
        if isinstance(v, (bool, np.bool_)):
            return [(hash_feature(col, "", bits), 1.0)] if v else []
        if isinstance(v, numbers.Number):
            fv = float(v)
            return [(hash_feature(col, "", bits), fv)] if fv != 0.0 else []
        if isinstance(v, (str, bytes)):
            s = v.decode() if isinstance(v, bytes) else v
            if split:
                return [(hash_feature(f"{col}_{tok}", "", bits), 1.0) for tok in s.split()]
            return [(hash_feature(f"{col}={s}", "", bits), 1.0)]
        if isinstance(v, dict):
            out = []
            for k, mv in v.items():
                if isinstance(mv, numbers.Number):
                    out.append((hash_feature(f"{col}.{k}", "", bits), float(mv)))
                else:
                    out.append((hash_feature(f"{col}.{k}={mv}", "", bits), 1.0))
            return out
        if isinstance(v, (list, tuple, np.ndarray)):
            return [(hash_feature(f"{col}_{i}", "", bits), float(x))
                    for i, x in enumerate(np.asarray(v, dtype=np.float64).ravel()) if x != 0.0]
        raise TypeError(f"cannot featurize {type(v).__name__} in column {col!r}")

    def _transform(self, df: DataFrame) -> DataFrame:
        cols = self.get("input_cols")
        if not cols:
            raise ValueError("input_cols must be set")
        self.require_columns(df, *cols)
        bits = self.get("num_bits")
        split_cols = set(self.get("string_split_cols") or ())
        out = self.get("output_col")
        sum_col = self.get("sum_collisions")

        # two passes: hash every partition first so the pad width is global
        # (keeps the output schema rectangular across partitions)
        all_rows: list[list[list[tuple[int, float]]]] = []
        for part in df.partitions:
            n = len(next(iter(part.values()))) if part else 0
            rows = []
            for i in range(n):
                feats: list[tuple[int, float]] = []
                for c in cols:
                    feats.extend(self._featurize_value(c, part[c][i], bits, c in split_cols))
                if sum_col and feats:
                    agg: dict[int, float] = {}
                    for k, v in feats:
                        agg[k] = agg.get(k, 0.0) + v
                    feats = list(agg.items())
                rows.append(feats)
            all_rows.append(rows)
        width = self.get("max_nnz") or max(
            (len(r) for rows in all_rows for r in rows), default=1)

        new_parts = []
        for part, rows in zip(df.partitions, all_rows):
            idx, val = pack_sparse(rows, width)
            res = dict(part)
            res[f"{out}_indices"] = idx
            res[f"{out}_values"] = val
            new_parts.append(res)
        return DataFrame(new_parts)
