"""Sync schedules for partition-replica training.

Reference: ``VowpalWabbitSyncSchedule.scala:72`` — decides, by row count, when
partitions AllReduce their weights between VW passes. Our fused GSPMD path
syncs every minibatch (strictly stronger); these objects exist for the
reference's explicit-schedule surface, used by
``learner.train_linear_partitioned``.
"""

from __future__ import annotations

__all__ = ["SyncSchedule", "SyncSchedulePassBoundary", "SyncScheduleRowCount"]


class SyncSchedule:
    """Yields (row_lo, row_hi) training windows; replicas average after each."""

    def boundaries(self, n_rows: int, num_passes: int):
        raise NotImplementedError


class SyncSchedulePassBoundary(SyncSchedule):
    """One sync per pass over the data (the reference default)."""

    def boundaries(self, n_rows: int, num_passes: int):
        for _ in range(max(num_passes, 1)):
            yield (0, n_rows)


class SyncScheduleRowCount(SyncSchedule):
    """Sync every ``rows_per_sync`` rows (the row-count schedule)."""

    def __init__(self, rows_per_sync: int):
        if rows_per_sync <= 0:
            raise ValueError("rows_per_sync must be positive")
        self.rows_per_sync = rows_per_sync

    def boundaries(self, n_rows: int, num_passes: int):
        for _ in range(max(num_passes, 1)):
            for lo in range(0, n_rows, self.rows_per_sync):
                yield (lo, min(lo + self.rows_per_sync, n_rows))
