"""Decision-service JSON → DataFrame (reference
``VowpalWabbitDSJsonTransformer.scala``: parses VW's dsjson logged-interaction
format into rows usable by the CB trainer and policy evaluators)."""

from __future__ import annotations

import json

import numpy as np

from ..core import DataFrame, Transformer
from ..core.params import Param

__all__ = ["VowpalWabbitDSJsonTransformer"]


class VowpalWabbitDSJsonTransformer(Transformer):
    feature_name = "vw"

    dsjson_col = Param("dsjson_col", "column of dsjson lines", default="value")

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("dsjson_col"))
        rows = []
        for line in df.collect_column(self.get("dsjson_col")):
            try:
                d = json.loads(line)
            except (json.JSONDecodeError, TypeError):
                continue
            labels = d.get("_labelIndex", d.get("_label_Action", 1) - 1)
            probs = d.get("p", [])
            chosen = int(labels) if not isinstance(labels, list) else int(labels[0])
            rows.append({
                "eventId": d.get("EventId", ""),
                "timestamp": d.get("Timestamp", ""),
                "cost": float(d.get("_label_cost", 0.0)),
                "probability": float(d.get("_label_probability",
                                           probs[0] if probs else 1.0)),
                "chosenAction": chosen + 1,  # 1-based like the reference
                "actionCount": len(d.get("a", [])) or len(probs) or 1,
                "probabilities": np.asarray(probs, np.float64),
                "context": json.dumps(d.get("c", {})),
            })
        if not rows:
            return DataFrame([{}])
        return DataFrame.from_rows(rows)
