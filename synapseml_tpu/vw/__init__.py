"""TPU-native Vowpal-Wabbit-equivalent module.

Replaces the reference's VW C++/JNI stack (``vw/`` module, SURVEY.md §2.2):
hashed-namespace featurization (``VowpalWabbitFeaturizer.scala``), online
linear learners synced by spanning-tree AllReduce
(``VowpalWabbitClusterUtil.scala:15-42``), contextual bandits
(``VowpalWabbitContextualBandit.scala``), and counterfactual policy
evaluation (``policyeval/``). TPU redesign: features hash into a fixed
2^bits weight vector; training is a jitted minibatch-SGD scan with the
cross-shard gradient reduction expressed through GSPMD sharding (every
minibatch syncs — strictly tighter than VW's pass-boundary AllReduce).
"""

from .featurizer import VowpalWabbitFeaturizer
from .estimators import (
    VowpalWabbitClassificationModel,
    VowpalWabbitClassifier,
    VowpalWabbitGeneric,
    VowpalWabbitGenericModel,
    VowpalWabbitRegressionModel,
    VowpalWabbitRegressor,
)
from .contextual_bandit import VowpalWabbitContextualBandit, VowpalWabbitContextualBanditModel
from .policyeval import (
    VowpalWabbitCSETransformer,
    cressie_read,
    cressie_read_interval,
    ips,
    snips,
)
from .dsjson import VowpalWabbitDSJsonTransformer
from .estimators import VowpalWabbitProgressive
from .sync import SyncSchedule, SyncSchedulePassBoundary, SyncScheduleRowCount

__all__ = [
    "VowpalWabbitProgressive", "SyncSchedule", "SyncSchedulePassBoundary", "SyncScheduleRowCount",
    "VowpalWabbitFeaturizer",
    "VowpalWabbitClassifier",
    "VowpalWabbitClassificationModel",
    "VowpalWabbitRegressor",
    "VowpalWabbitRegressionModel",
    "VowpalWabbitGeneric",
    "VowpalWabbitGenericModel",
    "VowpalWabbitContextualBandit",
    "VowpalWabbitContextualBanditModel",
    "VowpalWabbitCSETransformer",
    "VowpalWabbitDSJsonTransformer",
    "ips",
    "snips",
    "cressie_read",
    "cressie_read_interval",
]
