"""Contextual bandit training (CB-ADF) — TPU jitted IPS-weighted regression.

Reference: ``vw/.../VowpalWabbitContextualBandit.scala:27-376`` — VW's
``--cb_explore_adf`` mode driven through "example stacks" (shared-context
example + one example per action). Rebuilt: shared and per-action features
hash into the same weight space (interactions via hash offsets); training
minimizes IPS-weighted squared cost on the *chosen* action
(cost/probability importance weighting), which is VW's cb-type ``ips``
reduction to regression. Predict scores every action and returns both the
per-action scores and the greedy action.
"""

from __future__ import annotations

import numpy as np

from ..core import DataFrame, Estimator, Model
from ..core.params import ComplexParam, Param, TypeConverters
from .learner import LinearConfig, linear_predict, train_linear

__all__ = ["VowpalWabbitContextualBandit", "VowpalWabbitContextualBanditModel"]


_KNUTH = np.uint64(2654435761)


def _fmix32(x: np.ndarray) -> np.ndarray:
    """murmur3 finalizer — decorrelates combined hashes (without it,
    shared-index 0 interactions collide verbatim with action indices)."""
    m = np.uint64(0xFFFFFFFF)
    x = x & m
    x ^= x >> np.uint64(16)
    x = (x * np.uint64(0x85EBCA6B)) & m
    x ^= x >> np.uint64(13)
    x = (x * np.uint64(0xC2B2AE35)) & m
    x ^= x >> np.uint64(16)
    return x


def _stack_examples(shared_idx, shared_val, action_idx, action_val,
                    num_bits: int = 18, interactions: bool = True):
    """Concatenate shared-context features into each action's feature row
    (the reference's example-stack layout, ``ExampleStack:27``), plus hashed
    shared×action quadratic interactions — VW's ``-q SA``, without which a
    linear scorer cannot express context-dependent action preference."""
    n, a, d_a = action_idx.shape
    d_s = shared_idx.shape[1]
    parts_idx = [np.repeat(shared_idx[:, None, :], a, axis=1), action_idx]
    parts_val = [np.repeat(shared_val[:, None, :], a, axis=1), action_val]
    if interactions:
        mask = np.uint64((1 << num_bits) - 1)
        si = shared_idx.astype(np.uint64)[:, None, :, None]  # (N,1,Ds,1)
        ai = action_idx.astype(np.uint64)[:, :, None, :]  # (N,A,1,Da)
        qi = (_fmix32(si * _KNUTH + ai) & mask).astype(np.int32)  # (N,A,Ds,Da)
        qv = (shared_val[:, None, :, None] * action_val[:, :, None, :])
        parts_idx.append(qi.reshape(n, a, d_s * d_a))
        parts_val.append(qv.reshape(n, a, d_s * d_a).astype(np.float32))
    idx = np.concatenate(parts_idx, axis=2)
    val = np.concatenate(parts_val, axis=2)
    return idx, val


class _CBParams:
    shared_col = Param("shared_col", "shared-context feature column prefix "
                       "(<col>_indices/<col>_values)", default="shared")
    features_col = Param("features_col", "per-action features column prefix; "
                         "expects object columns of per-row (A, D) arrays or "
                         "flat (A*D,) with action_count", default="features")
    chosen_action_col = Param("chosen_action_col", "1-based chosen action index "
                              "(reference chosenActionCol)", default="chosenAction")
    label_col = Param("label_col", "cost of the chosen action (lower better)",
                      default="cost")
    probability_col = Param("probability_col", "logged P(chosen action)",
                            default="probability")
    prediction_col = Param("prediction_col", "output: per-action score vector",
                           default="prediction")
    num_bits = Param("num_bits", "hash space = 2^bits", default=18,
                     converter=TypeConverters.to_int)
    learning_rate = Param("learning_rate", "sgd learning rate", default=0.5,
                          converter=TypeConverters.to_float)
    num_passes = Param("num_passes", "data passes", default=1,
                       converter=TypeConverters.to_int)
    l1 = Param("l1", "L1 reg", default=0.0, converter=TypeConverters.to_float)
    l2 = Param("l2", "L2 reg", default=0.0, converter=TypeConverters.to_float)
    batch_size = Param("batch_size", "minibatch size", default=256,
                       converter=TypeConverters.to_int)
    seed = Param("seed", "shuffle seed", default=0, converter=TypeConverters.to_int)
    interactions = Param("interactions", "hashed shared x action quadratic features "
                         "(VW -q SA)", default=True, converter=TypeConverters.to_bool)

    def _sparse_pair(self, df: DataFrame, prefix: str):
        self.require_columns(df, f"{prefix}_indices", f"{prefix}_values")
        idx = np.asarray(df.collect_column(f"{prefix}_indices"))
        val = np.asarray(df.collect_column(f"{prefix}_values"))
        return idx, val

    def _action_sparse(self, df: DataFrame):
        """Per-action features: object column of (A, D) index/value arrays."""
        fc = self.get("features_col")
        idx_col = df.collect_column(f"{fc}_indices")
        val_col = df.collect_column(f"{fc}_values")
        if idx_col.dtype == object:
            a_max = max(np.asarray(v).shape[0] for v in idx_col)
            d_max = max(np.asarray(v).shape[1] for v in idx_col)
            n = len(idx_col)
            idx = np.zeros((n, a_max, d_max), np.int32)
            val = np.zeros((n, a_max, d_max), np.float32)
            for i, (iv, vv) in enumerate(zip(idx_col, val_col)):
                iv, vv = np.asarray(iv), np.asarray(vv)
                idx[i, : iv.shape[0], : iv.shape[1]] = iv
                val[i, : vv.shape[0], : vv.shape[1]] = vv
            return idx, val
        idx = np.asarray(idx_col, np.int32)
        val = np.asarray(val_col, np.float32)
        if idx.ndim != 3:
            raise ValueError(f"action features must be (N, A, D); got {idx.shape}")
        return idx, val


class VowpalWabbitContextualBandit(Estimator, _CBParams):
    feature_name = "vw"

    def _fit(self, df: DataFrame) -> "VowpalWabbitContextualBanditModel":
        self.require_columns(df, self.get("chosen_action_col"),
                             self.get("label_col"), self.get("probability_col"))
        sh_idx, sh_val = self._sparse_pair(df, self.get("shared_col"))
        a_idx, a_val = self._action_sparse(df)
        idx, val = _stack_examples(sh_idx, sh_val, a_idx, a_val,
                                   self.get("num_bits"), self.get("interactions"))
        n, a, d = idx.shape

        chosen = np.asarray(df.collect_column(self.get("chosen_action_col")), np.int64) - 1
        cost = np.asarray(df.collect_column(self.get("label_col")), np.float32)
        prob = np.asarray(df.collect_column(self.get("probability_col")), np.float32)
        if (chosen < 0).any() or (chosen >= a).any():
            raise ValueError("chosen_action_col must be 1-based within action count")

        # train on the chosen action's features, IPS importance weight 1/p
        rows = np.arange(n)
        cfg = LinearConfig(num_bits=self.get("num_bits"), loss="squared",
                           learning_rate=self.get("learning_rate"),
                           l1=self.get("l1"), l2=self.get("l2"),
                           num_passes=self.get("num_passes"),
                           batch_size=self.get("batch_size"), seed=self.get("seed"))
        w = train_linear(idx[rows, chosen], val[rows, chosen], cost, cfg,
                         weights=1.0 / np.clip(prob, 1e-6, None))
        model = VowpalWabbitContextualBanditModel(model_weights=w)
        model.set(**{k: v for k, v in self._param_values.items() if model.has_param(k)})
        return model

    def parallel_fit(self, df: DataFrame, param_grid: list[dict]) -> list["VowpalWabbitContextualBanditModel"]:
        """Grid fit (the reference parallelizes CB fits over a param grid,
        ``VowpalWabbitContextualBandit.scala`` parallelFit)."""
        out = []
        for params in param_grid:
            est = self.copy(params)
            out.append(est.fit(df))
        return out


class VowpalWabbitContextualBanditModel(Model, _CBParams):
    feature_name = "vw"

    model_weights = ComplexParam("model_weights", "weight vector (2^bits,)")

    def _transform(self, df: DataFrame) -> DataFrame:
        import jax.numpy as jnp

        sh_idx, sh_val = self._sparse_pair(df, self.get("shared_col"))
        a_idx, a_val = self._action_sparse(df)
        idx, val = _stack_examples(sh_idx, sh_val, a_idx, a_val,
                                   self.get("num_bits"), self.get("interactions"))
        n, a, d = idx.shape
        w = jnp.asarray(self.get("model_weights"))
        scores = np.asarray(linear_predict(w, jnp.asarray(idx.reshape(n * a, d)),
                                           jnp.asarray(val.reshape(n * a, d)))).reshape(n, a)
        return (df.with_column(self.get("prediction_col"), scores)
                  .with_column("predictedAction", np.argmin(scores, axis=1) + 1))
