"""Jitted sparse linear SGD — the VW native-learner replacement.

Reference: the C++ ``VowpalWabbitNative`` learn loop driven from
``VowpalWabbitBaseLearner.trainIteration`` (``VowpalWabbitBaseLearner.scala:135-188``)
with multi-pass + spanning-tree AllReduce weight sync at pass boundaries
(``VowpalWabbitClusterUtil.scala``, ``VowpalWabbitSyncSchedule.scala``).

TPU redesign: the weight vector (2^bits) lives replicated in HBM; each step
consumes a minibatch of padded-sparse rows (gather → dot → scatter-add
update), scanned over the whole pass inside one jit. When rows are sharded
over the mesh ``data`` axis, the per-minibatch gradient reduction is inserted
by GSPMD — every minibatch syncs, which strictly dominates VW's pass-boundary
AllReduce semantics.

Updates implement VW's core options: squared / logistic / hinge / quantile
losses, plain or AdaGrad-adaptive learning rates, L1/L2 regularization.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["LinearConfig", "train_linear", "linear_predict", "LOSSES"]

LOSSES = ("squared", "logistic", "hinge", "quantile")


class LinearConfig(NamedTuple):
    num_bits: int = 18
    loss: str = "squared"
    learning_rate: float = 0.5
    power_t: float = 0.5  # lr decay exponent (VW --power_t)
    l1: float = 0.0
    l2: float = 0.0
    num_passes: int = 1
    batch_size: int = 256
    adaptive: bool = True  # AdaGrad accumulator (VW default)
    quantile_tau: float = 0.5
    seed: int = 0


def _loss_grad(loss: str, pred: jax.Array, y: jax.Array, tau: float) -> jax.Array:
    """d(loss)/d(pred); labels: regression floats, or ±1 for classification."""
    if loss == "squared":
        return pred - y
    if loss == "logistic":
        return -y * jax.nn.sigmoid(-y * pred)
    if loss == "hinge":
        return jnp.where(y * pred < 1.0, -y, 0.0)
    if loss == "quantile":
        # pinball loss: L = (1-tau)*(pred-y) if pred>y else tau*(y-pred)
        e = pred - y
        return jnp.where(e >= 0, 1.0 - tau, -tau)
    raise ValueError(f"unknown loss {loss!r}; pick from {LOSSES}")


@functools.partial(jax.jit, static_argnames=("cfg", "num_batches"))
def _run_pass(w, acc, step0, idx, val, y, wt, cfg: LinearConfig, num_batches: int):
    """One pass over the (shuffled, batched) data: scan of minibatch updates."""

    def body(carry, batch):
        w, acc, t = carry
        bi, bv, by, bw = batch
        pred = jnp.sum(jnp.take(w, bi, axis=0) * bv, axis=1)  # (B,)
        g = _loss_grad(cfg.loss, pred, by, cfg.quantile_tau) * bw  # (B,)
        lr = cfg.learning_rate / jnp.power(t + 1.0, cfg.power_t)
        gv = g[:, None] * bv  # (B, D) per-feature gradient contributions
        if cfg.adaptive:
            acc = acc.at[bi].add(gv * gv)
            denom = jnp.sqrt(jnp.take(acc, bi, axis=0)) + 1e-8
            upd = gv / denom
        else:
            upd = gv
        w = w.at[bi].add(-lr * upd)
        if cfg.l2 > 0.0:
            w = w * (1.0 - lr * cfg.l2)
        if cfg.l1 > 0.0:
            w = jnp.sign(w) * jnp.maximum(jnp.abs(w) - lr * cfg.l1, 0.0)
        return (w, acc, t + 1.0), None

    batches = (idx.reshape(num_batches, -1, idx.shape[1]),
               val.reshape(num_batches, -1, val.shape[1]),
               y.reshape(num_batches, -1),
               wt.reshape(num_batches, -1))
    (w, acc, step), _ = jax.lax.scan(body, (w, acc, step0), batches)
    return w, acc, step


def train_linear(indices: np.ndarray, values: np.ndarray, labels: np.ndarray,
                 cfg: LinearConfig, weights: np.ndarray | None = None,
                 initial_weights: np.ndarray | None = None,
                 initial_state: tuple | None = None,
                 return_state: bool = False):
    """Train and return the weight vector (2^bits,) as numpy.

    ``initial_state``/``return_state`` carry the (AdaGrad accumulator, step
    counter) learner state across calls — partition-replica training syncs
    weights at schedule boundaries but must NOT restart the lr schedule."""
    n = indices.shape[0]
    dim = 1 << cfg.num_bits
    if initial_weights is not None and np.shape(initial_weights) != (dim,):
        raise ValueError(f"initial_weights shape {np.shape(initial_weights)} != "
                         f"({dim},) implied by num_bits={cfg.num_bits}")
    w = (jnp.asarray(initial_weights, jnp.float32) if initial_weights is not None
         else jnp.zeros(dim, jnp.float32))
    if initial_state is not None:
        acc = jnp.asarray(initial_state[0], jnp.float32)
        step = jnp.asarray(initial_state[1], jnp.float32)
    else:
        acc = jnp.full(dim, 1e-8, jnp.float32)
        step = jnp.asarray(0.0, jnp.float32)
    wt_np = np.ones(n, np.float32) if weights is None else np.asarray(weights, np.float32)

    bs = max(1, min(cfg.batch_size, n))
    rng = np.random.default_rng(cfg.seed)
    for _ in range(cfg.num_passes):
        order = rng.permutation(n)
        pad = (-n) % bs
        if pad:
            order = np.concatenate([order, order[:pad]])
        num_batches = len(order) // bs
        bi = jnp.asarray(indices[order])
        bv = jnp.asarray(values[order])
        by = jnp.asarray(np.asarray(labels, np.float32)[order])
        bw = jnp.asarray(wt_np[order] * (np.arange(len(order)) < n).astype(np.float32)
                         if pad else wt_np[order])
        w, acc, step = _run_pass(w, acc, step, bi, bv, by, bw, cfg, num_batches)
    if return_state:
        return np.asarray(w), (np.asarray(acc), float(step))
    return np.asarray(w)


@functools.partial(jax.jit, static_argnames=())
def linear_predict(w: jax.Array, idx: jax.Array, val: jax.Array) -> jax.Array:
    return jnp.sum(jnp.take(w, idx, axis=0) * val, axis=1)


@functools.partial(jax.jit, static_argnames=("cfg", "num_batches"))
def _run_pass_progressive(w, acc, step0, idx, val, y, wt, cfg: LinearConfig,
                          num_batches: int):
    """Like _run_pass, but also emits each batch's PRE-update predictions —
    VW's progressive validation (one-step-ahead) output."""

    def body(carry, batch):
        w, acc, t = carry
        bi, bv, by, bw = batch
        pred = jnp.sum(jnp.take(w, bi, axis=0) * bv, axis=1)  # pre-update
        g = _loss_grad(cfg.loss, pred, by, cfg.quantile_tau) * bw
        lr = cfg.learning_rate / jnp.power(t + 1.0, cfg.power_t)
        gv = g[:, None] * bv
        if cfg.adaptive:
            acc = acc.at[bi].add(gv * gv)
            denom = jnp.sqrt(jnp.take(acc, bi, axis=0)) + 1e-8
            upd = gv / denom
        else:
            upd = gv
        w = w.at[bi].add(-lr * upd)
        if cfg.l2 > 0.0:
            w = w * (1.0 - lr * cfg.l2)
        if cfg.l1 > 0.0:
            w = jnp.sign(w) * jnp.maximum(jnp.abs(w) - lr * cfg.l1, 0.0)
        return (w, acc, t + 1.0), pred

    batches = (idx.reshape(num_batches, -1, idx.shape[1]),
               val.reshape(num_batches, -1, val.shape[1]),
               y.reshape(num_batches, -1),
               wt.reshape(num_batches, -1))
    (w, acc, step), preds = jax.lax.scan(body, (w, acc, step0), batches)
    return w, acc, step, preds.reshape(-1)


def train_linear_progressive(indices: np.ndarray, values: np.ndarray,
                             labels: np.ndarray, cfg: LinearConfig,
                             weights: np.ndarray | None = None,
                             initial_weights: np.ndarray | None = None
                             ) -> tuple[np.ndarray, np.ndarray]:
    """Streaming-order single pass returning (weights, one-step-ahead preds).

    Reference: ``VowpalWabbitBaseProgressive.scala`` — transform-time online
    training where every row's output is the model's prediction BEFORE that
    row updates it. Rows are consumed in order (no shuffle); within a
    minibatch all rows see the pre-batch weights (batch_size=1 reproduces
    VW's strictly-online behavior)."""
    n = indices.shape[0]
    dim = 1 << cfg.num_bits
    w = (jnp.asarray(initial_weights, jnp.float32) if initial_weights is not None
         else jnp.zeros(dim, jnp.float32))
    acc = jnp.full(dim, 1e-8, jnp.float32)
    wt_np = np.ones(n, np.float32) if weights is None else np.asarray(weights, np.float32)

    bs = max(1, min(cfg.batch_size, n))
    pad = (-n) % bs
    order = np.arange(n + pad) % n if pad else np.arange(n)
    num_batches = len(order) // bs
    mask = (np.arange(len(order)) < n).astype(np.float32)
    w, acc, _, preds = _run_pass_progressive(
        w, acc, jnp.asarray(0.0, jnp.float32),
        jnp.asarray(indices[order]), jnp.asarray(values[order]),
        jnp.asarray(np.asarray(labels, np.float32)[order]),
        jnp.asarray(wt_np[order] * mask), cfg, num_batches)
    return np.asarray(w), np.asarray(preds)[:n]


def train_linear_partitioned(parts: list[tuple[np.ndarray, np.ndarray, np.ndarray]],
                             cfg: LinearConfig, sync_schedule=None,
                             initial_weights: np.ndarray | None = None) -> np.ndarray:
    """Partition-replica training with weight averaging at schedule boundaries.

    The explicit analog of VW's spanning-tree AllReduce driven by
    ``VowpalWabbitSyncSchedule.scala:72``: each partition trains its own
    replica between sync points; at each boundary the replicas all-reduce
    (average). ``parts``: per-partition (indices, values, labels). The fused
    GSPMD path (train_linear on sharded rows) syncs every minibatch and
    strictly dominates; this exists for reference-semantics parity and for
    DCN-limited topologies where sync frequency matters."""
    from .sync import SyncSchedulePassBoundary

    schedule = sync_schedule or SyncSchedulePassBoundary()
    dim = 1 << cfg.num_bits
    w = (np.asarray(initial_weights, np.float32) if initial_weights is not None
         else np.zeros(dim, np.float32))
    # windows cover the LARGEST partition so no partition's tail is dropped;
    # learner state (AdaGrad acc, step) persists per partition across windows
    # (weights average, state doesn't — matching VW, which AllReduces weights
    # but keeps each node's learner state)
    n_max = max(p[0].shape[0] for p in parts)
    states: list[tuple | None] = [None] * len(parts)
    for lo, hi in schedule.boundaries(n_max, cfg.num_passes):
        replicas = []
        for i, (idx, val, y) in enumerate(parts):
            m = idx.shape[0]
            s, e = min(lo, m), min(hi, m)
            if s >= e:
                replicas.append(w)
                continue
            sub_cfg = cfg._replace(num_passes=1)
            wi, states[i] = train_linear(idx[s:e], val[s:e], y[s:e], sub_cfg,
                                         initial_weights=w,
                                         initial_state=states[i],
                                         return_state=True)
            replicas.append(wi)
        w = np.mean(np.stack(replicas), axis=0)  # the AllReduce
    return w
