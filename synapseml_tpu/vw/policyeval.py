"""Counterfactual (off-)policy evaluation — IPS / SNIPS / Cressie-Read.

Reference: ``vw/.../policyeval/`` (``Ips.scala``, ``Snips.scala``,
``CressieRead.scala``, ``CressieReadInterval.scala``) implemented as Spark
UDAFs with Kahan-compensated sums (``KahanSum.scala:68``), plus the
``VowpalWabbitCSETransformer.scala:18`` counterfactual-selection-evaluation
wrapper. Here the aggregations are vectorized numpy (a partition is already a
column batch; no per-row UDAF loop needed); Kahan compensation is preserved
for the streaming ``KahanSum`` helper used by incremental consumers.

The Cressie-Read estimator follows Karampatziakis et al., "Empirical
Likelihood for Contextual Bandits" — the empirical-likelihood point estimate
solves a 1-D convex problem in the dual variable; the interval variant
profiles the likelihood against a chi-square cutoff, with importance weights
clipped to [wmin, wmax].
"""

from __future__ import annotations

import math

import numpy as np

from ..core import DataFrame, Transformer
from ..core.params import Param, TypeConverters

__all__ = ["KahanSum", "ips", "snips", "cressie_read", "cressie_read_interval",
           "VowpalWabbitCSETransformer"]


class KahanSum:
    """Numerically-stable streaming sum (reference ``KahanSum.scala``)."""

    def __init__(self):
        self._sum = 0.0
        self._c = 0.0

    def add(self, v: float) -> "KahanSum":
        y = v - self._c
        t = self._sum + y
        self._c = (t - self._sum) - y
        self._sum = t
        return self

    @property
    def value(self) -> float:
        return self._sum


def ips(weights: np.ndarray, rewards: np.ndarray) -> float:
    """Inverse propensity score: E[w * r] (``Ips.scala``)."""
    w = np.asarray(weights, np.float64)
    r = np.asarray(rewards, np.float64)
    return float(np.mean(w * r))


def snips(weights: np.ndarray, rewards: np.ndarray) -> float:
    """Self-normalized IPS: sum(w*r)/sum(w) (``Snips.scala``)."""
    w = np.asarray(weights, np.float64)
    r = np.asarray(rewards, np.float64)
    denom = w.sum()
    return float((w * r).sum() / denom) if denom > 0 else 0.0


def _el_dual(w: np.ndarray, lam: float) -> float:
    # derivative of the EL log-likelihood wrt lambda; root gives the MLE
    return float(np.mean((w - 1.0) / (1.0 + lam * (w - 1.0))))


def cressie_read(weights: np.ndarray, rewards: np.ndarray,
                 wmin: float = 0.0, wmax: float = math.inf) -> float:
    """Empirical-likelihood point estimate of the policy value
    (``CressieRead.scala``). Solves for the dual variable by bisection, then
    returns the tilted average of w*r."""
    w = np.clip(np.asarray(weights, np.float64), wmin, min(wmax, 1e12))
    r = np.asarray(rewards, np.float64)
    if len(w) == 0:
        return 0.0
    # lambda must keep 1 + lam*(w-1) > 0 for all w
    lo_bound = -1.0 / max(w.max() - 1.0, 1e-12) + 1e-9
    hi_bound = min(1.0 / max(1.0 - w.min(), 1e-12) - 1e-9, 1e9)
    d0 = _el_dual(w, 0.0)  # = mean(w) - 1
    if abs(d0) < 1e-12:
        lam = 0.0
    else:
        # the dual is monotone decreasing in lam; bracket from 0 toward the
        # boundary matching d0's sign; if no crossing, the EL solution is at
        # the boundary (e.g. all w >= 1 -> mass concentrates on w == min)
        lo, hi = (0.0, hi_bound) if d0 > 0 else (lo_bound, 0.0)
        f_lo = _el_dual(w, lo)
        if f_lo * _el_dual(w, hi) > 0:
            lam = hi if d0 > 0 else lo
        else:
            for _ in range(100):
                mid = 0.5 * (lo + hi)
                f_mid = _el_dual(w, mid)
                if f_lo * f_mid <= 0:
                    hi = mid
                else:
                    lo, f_lo = mid, f_mid
            lam = 0.5 * (lo + hi)
    p = 1.0 / (1.0 + lam * (w - 1.0))
    p = p / p.sum()
    return float(np.sum(p * w * r))


def cressie_read_interval(weights: np.ndarray, rewards: np.ndarray,
                          alpha: float = 0.05, wmin: float = 0.0,
                          wmax: float = 100.0,
                          rmin: float = 0.0, rmax: float = 1.0) -> tuple[float, float]:
    """EL confidence interval (``CressieReadInterval.scala``): profile the
    estimate over reward bounds with weight clipping; returns (lower, upper)."""
    w = np.clip(np.asarray(weights, np.float64), wmin, wmax)
    r = np.clip(np.asarray(rewards, np.float64), rmin, rmax)
    n = len(w)
    if n == 0:
        return (rmin, rmax)
    point = cressie_read(w, r)
    # Gaussian-approximate EL profile half-width (matches the reference's
    # chi-square(1) cutoff asymptotics)
    z = 1.959963984540054 if abs(alpha - 0.05) < 1e-9 else _z_for(alpha)
    var = np.var(w * r) + 1e-12
    half = z * math.sqrt(var / n)
    return (max(point - half, rmin * min(1.0, w.min() if n else 1.0)),
            min(point + half, rmax * w.max() if n else rmax))


def _z_for(alpha: float) -> float:
    # inverse normal CDF via Acklam's rational approximation (two-sided)
    from statistics import NormalDist

    return NormalDist().inv_cdf(1.0 - alpha / 2.0)


class VowpalWabbitCSETransformer(Transformer):
    """Counterfactual selection evaluation: aggregates logged bandit rows into
    per-policy value estimates (reference ``VowpalWabbitCSETransformer.scala``).

    Input: logged probability col, reward col(s), and the evaluated policy's
    probability col; output: one row with IPS/SNIPS/CR estimates + interval.
    """

    feature_name = "vw"

    logged_probability_col = Param("logged_probability_col",
                                   "logged P(action) column", default="probLog")
    target_probability_col = Param("target_probability_col",
                                   "evaluated policy P(action) column", default="probPred")
    reward_col = Param("reward_col", "reward column", default="reward")
    min_importance_weight = Param("min_importance_weight", "w clip lower", default=0.0,
                                  converter=TypeConverters.to_float)
    max_importance_weight = Param("max_importance_weight", "w clip upper", default=100.0,
                                  converter=TypeConverters.to_float)

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("logged_probability_col"),
                             self.get("target_probability_col"), self.get("reward_col"))
        p_log = np.asarray(df.collect_column(self.get("logged_probability_col")), np.float64)
        p_tgt = np.asarray(df.collect_column(self.get("target_probability_col")), np.float64)
        r = np.asarray(df.collect_column(self.get("reward_col")), np.float64)
        w = p_tgt / np.clip(p_log, 1e-9, None)
        wmin, wmax = self.get("min_importance_weight"), self.get("max_importance_weight")
        lo, hi = cressie_read_interval(w, r, wmin=wmin, wmax=wmax,
                                       rmin=float(r.min(initial=0.0)),
                                       rmax=float(r.max(initial=1.0)))
        return DataFrame.from_dict({
            "count": np.array([len(r)]),
            "ips": np.array([ips(w, r)]),
            "snips": np.array([snips(w, r)]),
            "cressieRead": np.array([cressie_read(w, r, wmin, wmax)]),
            "cressieReadLower": np.array([lo]),
            "cressieReadUpper": np.array([hi]),
        })
