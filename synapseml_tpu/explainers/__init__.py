"""Model-agnostic local explainers (responsible AI).

Reference: ``core/.../explainers/`` (SURVEY.md §2.5) — ``LIMEBase:137`` +
Tabular/Vector/Image/Text LIME, ``KernelSHAPBase:37`` + variants, samplers,
a lasso solver on breeze, and ``ICETransformer:126`` (ICE/PDP).

TPU design: all perturbed samples for a whole partition are scored in ONE
model.transform call (the underlying model batches them onto the device —
SURVEY.md §7 step 8's "perturbation batches through the TPU inference path"),
then the local weighted linear models are solved per row with vectorized
numpy/jax least squares.
"""

from .base import row_rng
from .lasso import lasso_regression, weighted_least_squares
from .lime import ImageLIME, TabularLIME, TextLIME, VectorLIME
from .shap import ImageSHAP, TabularSHAP, TextSHAP, VectorSHAP
from .ice import ICETransformer

__all__ = [
    "TabularLIME", "VectorLIME", "ImageLIME", "TextLIME",
    "TabularSHAP", "VectorSHAP", "ImageSHAP", "TextSHAP",
    "ICETransformer", "lasso_regression", "weighted_least_squares",
    "row_rng",
]
