"""LIME — local interpretable model-agnostic explanations.

Reference: ``explainers/LIMEBase.scala:137`` + ``{Tabular,Vector,Image,Text}LIME``
and ``Sampler.scala``. Per row: draw perturbed samples, score them through the
model, weight by proximity kernel, fit a weighted lasso; coefficients are the
explanation (one vector per target class).
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from .base import LocalExplainerBase, row_rng
from .lasso import lasso_regression

__all__ = ["TabularLIME", "VectorLIME", "ImageLIME", "TextLIME"]


def _kernel_weight(dist: np.ndarray, width: float) -> np.ndarray:
    return np.exp(-(dist ** 2) / (width ** 2))


class _LIMEBase(LocalExplainerBase):
    kernel_width = Param("kernel_width", "proximity kernel width", default=0.75,
                         converter=TypeConverters.to_float)
    regularization = Param("regularization", "lasso alpha", default=0.001,
                           converter=TypeConverters.to_float)

    def _fit_surrogates(self, Z: np.ndarray, scores: np.ndarray,
                        dist: np.ndarray) -> np.ndarray:
        """Z: [S, M] binary/continuous design; scores: [S, T]; dist: [S]."""
        w = _kernel_weight(dist, self.get("kernel_width"))
        coefs = []
        for t in range(scores.shape[1]):
            beta, _ = lasso_regression(Z, scores[:, t], w,
                                       alpha=self.get("regularization"))
            coefs.append(beta)
        return np.stack(coefs)  # [T, M]


class VectorLIME(_LIMEBase):
    """(ref ``VectorLIME.scala``) rows hold fixed-length feature vectors;
    perturbations are gaussian around the instance scaled by background std."""

    feature_name = "explainers"

    input_col = Param("input_col", "feature vector column", default="features")
    background_data = ComplexParam("background_data",
                                   "background DataFrame for feature stats",
                                   default=None)

    def _background_stats(self, df: DataFrame):
        bg = self.get("background_data") or df
        X = np.stack([np.asarray(v, np.float64)
                      for v in bg.collect_column(self.get("input_col"))])
        std = X.std(axis=0)
        return np.where(std > 1e-12, std, 1.0)

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        std = self._background_stats(df)
        S = self.get("num_samples")
        seed = self.get("seed")

        def per_part(p):
            X = np.stack([np.asarray(v, np.float64) for v in p[self.get("input_col")]])
            n, M = X.shape
            # one neighborhood draw per (seed, row content): the same row
            # gets the same perturbations on any shard/partitioning
            noise = np.stack([row_rng(seed, X[i]).standard_normal((S, M))
                              for i in range(n)])
            samples = X[:, None, :] + noise * std[None, None, :]
            flat = samples.reshape(n * S, M).astype(np.float32)
            if self._use_fused():
                from ..rai.fused import fused_array_scores

                scores = fused_array_scores(self, flat)
            else:
                scores = self._score_samples(
                    DataFrame.from_dict({self.get("input_col"): flat}))
            scores = scores.reshape(n, S, -1)
            dist = np.sqrt((noise ** 2).mean(axis=2))     # [n, S] scaled distance
            expl = []
            for i in range(n):
                Zc = (samples[i] - X[i]) / std            # standardized design
                expl.append(self._fit_surrogates(Zc, scores[i], dist[i]))
            q = dict(p)
            q[self.get("output_col")] = self._pack_explanations(expl)
            return q

        return df.map_partitions(per_part)


class TabularLIME(VectorLIME):
    """(ref ``TabularLIME.scala``) like VectorLIME but over named numeric
    columns; ``input_cols`` are assembled into a vector on the fly."""

    input_cols = ComplexParam("input_cols", "numeric feature columns")

    def _transform(self, df: DataFrame) -> DataFrame:
        cols = self.get("input_cols")
        self.require_columns(df, *cols)
        vec_col = "_lime_features"
        assembled = df.with_column(
            vec_col, lambda p: np.stack([np.asarray(p[c], np.float32) for c in cols], axis=1))

        inner_model = self.get("model")

        class _Unpack:
            """Present the vector back to the model as named columns."""

            def transform(self_inner, sdf: DataFrame) -> DataFrame:
                X = np.asarray(np.stack(list(sdf.collect_column(vec_col))))
                data = {c: X[:, i] for i, c in enumerate(cols)}
                return inner_model.transform(DataFrame.from_dict(data))

        proxy = self.copy()
        proxy.set(model=_Unpack(), input_col=vec_col)
        if self.get("background_data") is not None:
            bgd = self.get("background_data")
            proxy.set(background_data=bgd.with_column(
                vec_col, lambda p: np.stack([np.asarray(p[c], np.float32)
                                             for c in cols], axis=1)))
        out = VectorLIME._transform(proxy, assembled)
        return out.drop(vec_col)


class ImageLIME(_LIMEBase):
    """(ref ``ImageLIME.scala``) superpixel on/off perturbations; the binary
    design matrix is the superpixel state vector."""

    feature_name = "explainers"

    input_col = Param("input_col", "image column", default="image")
    superpixel_col = Param("superpixel_col", "precomputed label map column "
                           "(None = run SLIC)", default=None)
    cell_size = Param("cell_size", "SLIC seed pitch", default=16.0,
                      converter=TypeConverters.to_float)
    modifier = Param("modifier", "SLIC color weight", default=130.0,
                     converter=TypeConverters.to_float)
    sampling_fraction = Param("sampling_fraction", "probability a superpixel stays on",
                              default=0.7, converter=TypeConverters.to_float)

    def _transform(self, df: DataFrame) -> DataFrame:
        from ..image.superpixel import slic_segments
        from ..image.transforms import as_image

        self.require_columns(df, self.get("input_col"))
        S = self.get("num_samples")
        seed = self.get("seed")
        frac = self.get("sampling_fraction")

        def per_part(p):
            imgs = [as_image(v) for v in p[self.get("input_col")]]
            sp_col = self.get("superpixel_col")
            label_maps = (list(p[sp_col]) if sp_col and sp_col in p else
                          [slic_segments(im, self.get("cell_size"), self.get("modifier"))
                           for im in imgs])
            designs, blocks = [], []
            for im, labels in zip(imgs, label_maps):
                K = int(labels.max()) + 1
                states = row_rng(seed, im).random((S, K)) < frac  # [S, K]
                states[0] = True                          # include the full image
                masks = states[:, labels]                 # [S, H, W]
                designs.append(states)
                blocks.append(im[None] * masks[:, :, :, None])
            builder = lambda samples: DataFrame.from_dict(  # noqa: E731
                {self.get("input_col"): [s for s in samples]})
            if self._use_fused():
                from ..rai.fused import fused_block_scores

                score_blocks = fused_block_scores(self, blocks, builder)
            else:
                score_blocks = [self._score_samples(builder(b))
                                for b in blocks]
            expl = []
            for states, scores in zip(designs, score_blocks):
                dist = 1.0 - states.mean(axis=1)          # fraction turned off
                expl.append(self._fit_surrogates(states.astype(np.float64),
                                                 scores, dist))
            q = dict(p)
            q[self.get("output_col")] = self._pack_explanations(expl)
            return q

        return df.map_partitions(per_part)


class TextLIME(_LIMEBase):
    """(ref ``TextLIME.scala``) token on/off perturbations."""

    feature_name = "explainers"

    input_col = Param("input_col", "text column", default="text")
    token_col = Param("token_col", "output column for the token list",
                      default="tokens")
    sampling_fraction = Param("sampling_fraction", "probability a token stays",
                              default=0.7, converter=TypeConverters.to_float)

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        S = self.get("num_samples")
        seed = self.get("seed")
        frac = self.get("sampling_fraction")

        def per_part(p):
            texts = [str(t) for t in p[self.get("input_col")]]
            token_rows = np.empty(len(texts), dtype=object)
            designs, blocks = [], []
            for r, text in enumerate(texts):
                tokens = text.split()
                token_rows[r] = np.asarray(tokens, dtype=object)
                K = max(len(tokens), 1)
                states = row_rng(seed, text).random((S, K)) < frac
                states[0] = True
                designs.append(states)
                blocks.append([" ".join(t for t, on in zip(tokens, st) if on)
                               for st in states])
            builder = lambda samples: DataFrame.from_dict(  # noqa: E731
                {self.get("input_col"): samples})
            if self._use_fused():
                from ..rai.fused import fused_block_scores

                score_blocks = fused_block_scores(self, blocks, builder)
            else:
                score_blocks = [self._score_samples(builder(b))
                                for b in blocks]
            expl = []
            for states, scores in zip(designs, score_blocks):
                dist = 1.0 - states.mean(axis=1)
                expl.append(self._fit_surrogates(states.astype(np.float64),
                                                 scores, dist))
            q = dict(p)
            q[self.get("output_col")] = self._pack_explanations(expl)
            q[self.get("token_col")] = token_rows
            return q

        return df.map_partitions(per_part)
