"""ICETransformer — individual conditional expectation + partial dependence
(reference ``explainers/ICETransformer.scala:126``).

For each requested feature: build a value grid (numeric quantile grid or the
categorical value set), clone every row once per grid value with the feature
replaced, score everything in one model.transform, and emit per-row curves
(kind='individual') or the average curve (kind='average', i.e. PDP).
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from .base import LocalExplainerBase

__all__ = ["ICETransformer"]


class ICETransformer(LocalExplainerBase):
    feature_name = "explainers"

    categorical_features = ComplexParam("categorical_features",
                                        "categorical feature columns", default=None)
    numeric_features = ComplexParam("numeric_features",
                                    "numeric feature columns", default=None)
    kind = Param("kind", "individual | average", default="individual",
                 validator=lambda v: v in ("individual", "average"))
    num_splits = Param("num_splits", "grid points for numeric features", default=10,
                       converter=TypeConverters.to_int)

    def _grid(self, df: DataFrame, col: str, categorical: bool) -> np.ndarray:
        vals = np.asarray(df.collect_column(col))
        if categorical:
            return np.unique(vals)
        qs = np.linspace(0.0, 1.0, self.get("num_splits"))
        return np.unique(np.quantile(vals.astype(np.float64), qs))

    def _transform(self, df: DataFrame) -> DataFrame:
        cats = list(self.get("categorical_features") or [])
        nums = list(self.get("numeric_features") or [])
        if not cats and not nums:
            raise ValueError("ICETransformer: set categorical_features and/or "
                             "numeric_features")
        self.require_columns(df, *(cats + nums))
        n = df.count()
        whole = df.collect()
        out_cols: dict = {}
        for col in cats + nums:
            grid = self._grid(df, col, categorical=col in cats)
            G = len(grid)
            # replicate all rows G times with col swept over the grid
            rep = {k: np.concatenate([v] * G, axis=0) if v.dtype != object
                   else np.concatenate([v] * G)
                   for k, v in whole.items()}
            rep[col] = np.repeat(grid, n)
            scores = None
            if self._use_fused():
                from ..rai.fused import fused_columnar_scores

                # G*n grid clones in ladder-bucketed mega-batches through
                # the model's own score fn (None when the model declares no
                # columnar score path — fall through to the serial call)
                scores = fused_columnar_scores(self, rep)
            if scores is None:
                scores = self._score_samples(DataFrame.from_dict(rep))  # [G*n, T]
            curves = scores.reshape(G, n, -1).transpose(1, 0, 2)    # [n, G, T]
            if self.get("kind") == "average":
                pdp = curves.mean(axis=0)                           # [G, T]
                cell = np.empty(1, dtype=object)
                cell[0] = {str(g): pdp[j].tolist() for j, g in enumerate(grid)}
                out_cols[f"{col}_dependence"] = cell
            else:
                col_arr = np.empty(n, dtype=object)
                for i in range(n):
                    col_arr[i] = {str(g): curves[i, j].tolist()
                                  for j, g in enumerate(grid)}
                out_cols[f"{col}_dependence"] = col_arr
        if self.get("kind") == "average":
            return DataFrame([out_cols])
        out = df
        for k, v in out_cols.items():
            out = out.with_column(k, v)
        return out
