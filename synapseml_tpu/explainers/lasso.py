"""Weighted linear solvers for the local surrogate models.

Reference: ``explainers/LassoRegression.scala`` (90 LoC on breeze) — weighted
lasso via coordinate descent — and the weighted least squares KernelSHAP uses.
"""

from __future__ import annotations

import numpy as np

__all__ = ["lasso_regression", "weighted_least_squares"]


def weighted_least_squares(X: np.ndarray, y: np.ndarray, w: np.ndarray,
                           ridge: float = 1e-8) -> tuple[np.ndarray, float]:
    """argmin_b sum_i w_i (y_i - b0 - X_i b)^2. Returns (coefs, intercept)."""
    Xb = np.concatenate([X, np.ones((len(X), 1))], axis=1)
    W = w[:, None]
    A = Xb.T @ (W * Xb) + ridge * np.eye(Xb.shape[1])
    b = Xb.T @ (w * y)
    sol = np.linalg.solve(A, b)
    return sol[:-1], float(sol[-1])


def lasso_regression(X: np.ndarray, y: np.ndarray, w: np.ndarray,
                     alpha: float = 0.01, n_iter: int = 200,
                     tol: float = 1e-7) -> tuple[np.ndarray, float]:
    """Weighted lasso by cyclic coordinate descent with soft thresholding
    (the reference's breeze solver, ``LassoRegression.scala``)."""
    n, d = X.shape
    w = np.asarray(w, np.float64)
    sw = w.sum() or 1.0
    # center by weighted means so the intercept drops out of the descent
    x_mean = (w[:, None] * X).sum(0) / sw
    y_mean = float((w * y).sum() / sw)
    Xc = X - x_mean
    yc = y - y_mean
    beta = np.zeros(d)
    col_sq = (w[:, None] * Xc * Xc).sum(0)
    resid = yc - Xc @ beta
    for _ in range(n_iter):
        max_delta = 0.0
        for j in range(d):
            if col_sq[j] <= 1e-12:
                continue
            rho = float((w * (resid + Xc[:, j] * beta[j]) * Xc[:, j]).sum())
            new_b = np.sign(rho) * max(abs(rho) - alpha * sw, 0.0) / col_sq[j]
            delta = new_b - beta[j]
            if delta != 0.0:
                resid -= Xc[:, j] * delta
                beta[j] = new_b
                max_delta = max(max_delta, abs(delta))
        if max_delta < tol:
            break
    intercept = y_mean - float(x_mean @ beta)
    return beta, intercept
