"""KernelSHAP — Shapley values via the weighted-least-squares kernel trick.

Reference: ``explainers/KernelSHAPBase.scala:37`` + variants and
``KernelSHAPSampler.scala``: sample coalitions z in {0,1}^M with Shapley-kernel
weights pi(z) = (M-1) / (C(M,|z|) |z| (M-|z|)), score f(h(z)), solve the
constrained weighted regression so that phi0 = f(background) and
sum(phi) + phi0 = f(x).
"""

from __future__ import annotations

import math

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from .base import LocalExplainerBase
from .lasso import weighted_least_squares

__all__ = ["TabularSHAP", "VectorSHAP", "ImageSHAP", "TextSHAP"]


def shapley_kernel_weight(M: int, s: int) -> float:
    if s == 0 or s == M:
        return 1e6  # enforced almost exactly (reference uses infinite weight)
    return (M - 1) / (math.comb(M, s) * s * (M - s))


def sample_coalitions(M: int, n_samples: int, rng) -> tuple[np.ndarray, np.ndarray]:
    """[S, M] binary coalition matrix + kernel weights; always includes the
    empty and full coalitions (they pin phi0 and the efficiency constraint)."""
    states = [np.zeros(M, bool), np.ones(M, bool)]
    weights = [shapley_kernel_weight(M, 0), shapley_kernel_weight(M, M)]
    # enumerate when feasible, sample otherwise (reference sampler behavior)
    if 2 ** M <= n_samples:
        for code in range(1, 2 ** M - 1):
            z = np.asarray([(code >> b) & 1 for b in range(M)], bool)
            states.append(z)
            weights.append(shapley_kernel_weight(M, int(z.sum())))
    else:
        sizes = np.arange(1, M)
        size_w = np.asarray([shapley_kernel_weight(M, s) * math.comb(M, s)
                             for s in sizes])
        size_p = size_w / size_w.sum()
        for _ in range(n_samples - 2):
            s = rng.choice(sizes, p=size_p)
            z = np.zeros(M, bool)
            z[rng.choice(M, size=s, replace=False)] = True
            states.append(z)
            weights.append(shapley_kernel_weight(M, s))
    return np.asarray(states), np.asarray(weights, np.float64)


def solve_shap(Z: np.ndarray, y: np.ndarray, w: np.ndarray) -> np.ndarray:
    """Weighted least squares on the coalition design; returns [M+1] with
    phi0 last."""
    coefs, intercept = weighted_least_squares(Z.astype(np.float64), y, w)
    return np.concatenate([coefs, [intercept]])


class _KernelSHAPBase(LocalExplainerBase):
    def _explain_rows(self, make_samples, K_of_row, rows, score_input_builder):
        """Shared loop: rows -> coalitions -> model scores -> phi vectors."""
        rng = np.random.default_rng(self.get("seed"))
        S = self.get("num_samples")
        expl = []
        for r in rows:
            K = K_of_row(r)
            states, w = sample_coalitions(K, S, rng)
            samples = make_samples(r, states)
            scores = self._score_samples(score_input_builder(samples))
            phis = [solve_shap(states, scores[:, t], w)
                    for t in range(scores.shape[1])]
            expl.append(np.stack(phis))  # [T, K+1]
        return expl


class VectorSHAP(_KernelSHAPBase):
    """(ref ``VectorSHAP.scala``) feature-vector rows; off features are
    replaced by the background mean (or sampled background rows)."""

    feature_name = "explainers"

    input_col = Param("input_col", "feature vector column", default="features")
    background_data = ComplexParam("background_data", "background DataFrame",
                                   default=None)

    def _background(self, df: DataFrame) -> np.ndarray:
        bg = self.get("background_data") or df
        X = np.stack([np.asarray(v, np.float64)
                      for v in bg.collect_column(self.get("input_col"))])
        return X.mean(axis=0)

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        bg = self._background(df)

        def per_part(p):
            X = np.stack([np.asarray(v, np.float64) for v in p[self.get("input_col")]])

            expl = self._explain_rows(
                make_samples=lambda x, states: np.where(states, x[None, :], bg[None, :]),
                K_of_row=lambda x: len(x),
                rows=list(X),
                score_input_builder=lambda samples: DataFrame.from_dict(
                    {self.get("input_col"): samples.astype(np.float32)}),
            )
            q = dict(p)
            q[self.get("output_col")] = self._pack_explanations(expl)
            return q

        return df.map_partitions(per_part)


class TabularSHAP(VectorSHAP):
    """(ref ``TabularSHAP.scala``) named numeric columns."""

    input_cols = ComplexParam("input_cols", "numeric feature columns")

    def _transform(self, df: DataFrame) -> DataFrame:
        cols = self.get("input_cols")
        self.require_columns(df, *cols)
        vec_col = "_shap_features"
        assembled = df.with_column(
            vec_col, lambda p: np.stack([np.asarray(p[c], np.float32) for c in cols], axis=1))
        inner_model = self.get("model")

        class _Unpack:
            def transform(self_inner, sdf: DataFrame) -> DataFrame:
                X = np.asarray(np.stack(list(sdf.collect_column(vec_col))))
                return inner_model.transform(DataFrame.from_dict(
                    {c: X[:, i] for i, c in enumerate(cols)}))

        proxy = self.copy()
        proxy.set(model=_Unpack(), input_col=vec_col)
        if self.get("background_data") is not None:
            bgd = self.get("background_data")
            proxy.set(background_data=bgd.with_column(
                vec_col, lambda p: np.stack([np.asarray(p[c], np.float32) for c in cols], axis=1)))
        out = VectorSHAP._transform(proxy, assembled)
        return out.drop(vec_col)


class ImageSHAP(_KernelSHAPBase):
    """(ref ``ImageSHAP.scala``) superpixels as players; off superpixels
    blanked to the image mean color."""

    feature_name = "explainers"

    input_col = Param("input_col", "image column", default="image")
    cell_size = Param("cell_size", "SLIC seed pitch", default=16.0,
                      converter=TypeConverters.to_float)
    modifier = Param("modifier", "SLIC color weight", default=130.0,
                     converter=TypeConverters.to_float)

    def _transform(self, df: DataFrame) -> DataFrame:
        from ..image.superpixel import slic_segments
        from ..image.transforms import as_image

        self.require_columns(df, self.get("input_col"))

        def per_part(p):
            imgs = [as_image(v) for v in p[self.get("input_col")]]
            expl = []
            for im in imgs:
                labels = slic_segments(im, self.get("cell_size"), self.get("modifier"))
                fill = im.mean(axis=(0, 1))

                def make_samples(_, states, im=im, labels=labels, fill=fill):
                    masks = states[:, labels]              # [S, H, W]
                    return np.where(masks[:, :, :, None], im[None], fill[None, None, None, :])

                phis = self._explain_rows(
                    make_samples=make_samples,
                    K_of_row=lambda _im, K=int(labels.max()) + 1: K,
                    rows=[im],
                    score_input_builder=lambda samples: DataFrame.from_dict(
                        {self.get("input_col"): [s for s in samples]}),
                )
                expl.extend(phis)
            q = dict(p)
            q[self.get("output_col")] = self._pack_explanations(expl)
            return q

        return df.map_partitions(per_part)


class TextSHAP(_KernelSHAPBase):
    """(ref ``TextSHAP.scala``) tokens as players; off tokens dropped."""

    feature_name = "explainers"

    input_col = Param("input_col", "text column", default="text")
    token_col = Param("token_col", "token list output column", default="tokens")

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))

        def per_part(p):
            texts = [str(t) for t in p[self.get("input_col")]]
            expl = []
            token_rows = np.empty(len(texts), dtype=object)
            for r, text in enumerate(texts):
                tokens = text.split()
                token_rows[r] = np.asarray(tokens, dtype=object)

                def make_samples(_, states, tokens=tokens):
                    return [" ".join(t for t, on in zip(tokens, st) if on)
                            for st in states]

                phis = self._explain_rows(
                    make_samples=make_samples,
                    K_of_row=lambda _t, K=max(len(tokens), 1): K,
                    rows=[text],
                    score_input_builder=lambda samples: DataFrame.from_dict(
                        {self.get("input_col"): samples}),
                )
                expl.extend(phis)
            q = dict(p)
            q[self.get("output_col")] = self._pack_explanations(expl)
            q[self.get("token_col")] = token_rows
            return q

        return df.map_partitions(per_part)
