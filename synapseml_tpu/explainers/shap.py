"""KernelSHAP — Shapley values via the weighted-least-squares kernel trick.

Reference: ``explainers/KernelSHAPBase.scala:37`` + variants and
``KernelSHAPSampler.scala``: sample coalitions z in {0,1}^M with Shapley-kernel
weights pi(z) = (M-1) / (C(M,|z|) |z| (M-|z|)), score f(h(z)), solve the
constrained weighted regression so that phi0 = f(background) and
sum(phi) + phi0 = f(x).
"""

from __future__ import annotations

import math

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from .base import LocalExplainerBase, row_rng
from .lasso import weighted_least_squares

__all__ = ["TabularSHAP", "VectorSHAP", "ImageSHAP", "TextSHAP"]


def shapley_kernel_weight(M: int, s: int) -> float:
    if s == 0 or s == M:
        return 1e6  # enforced almost exactly (reference uses infinite weight)
    return (M - 1) / (math.comb(M, s) * s * (M - s))


# (M, n_samples) -> enumerated (states, weights): in the exhaustive regime
# the design is rng-free and identical for every row, so rows share one copy
# instead of re-enumerating 2^M coalitions per row
_ENUM_DESIGNS: dict = {}


def sample_coalitions(M: int, n_samples: int, rng) -> tuple[np.ndarray, np.ndarray]:
    """[S, M] binary coalition matrix + kernel weights; always includes the
    empty and full coalitions (they pin phi0 and the efficiency constraint)."""
    # enumerate when feasible, sample otherwise (reference sampler behavior)
    if 2 ** M <= n_samples:
        hit = _ENUM_DESIGNS.get((M, n_samples))
        if hit is not None:
            return hit
        states = [np.zeros(M, bool), np.ones(M, bool)]
        weights = [shapley_kernel_weight(M, 0), shapley_kernel_weight(M, M)]
        for code in range(1, 2 ** M - 1):
            z = np.asarray([(code >> b) & 1 for b in range(M)], bool)
            states.append(z)
            weights.append(shapley_kernel_weight(M, int(z.sum())))
        out = (np.asarray(states), np.asarray(weights, np.float64))
        if len(_ENUM_DESIGNS) < 64:
            _ENUM_DESIGNS[(M, n_samples)] = out
        return out
    pinned = np.stack([np.zeros(M, bool), np.ones(M, bool)])
    pinned_w = np.asarray([shapley_kernel_weight(M, 0),
                           shapley_kernel_weight(M, M)], np.float64)
    sizes = np.arange(1, M)
    size_w = np.asarray([shapley_kernel_weight(M, s) * math.comb(M, s)
                         for s in sizes])
    size_p = size_w / size_w.sum()
    n_draw = max(n_samples - 2, 0)
    s_draw = rng.choice(sizes, size=n_draw, p=size_p)
    # the s smallest of M iid uniform keys are a uniform random size-s
    # subset, so one double argsort yields every sample's membership mask
    ranks = rng.random((n_draw, M)).argsort(axis=1).argsort(axis=1)
    wt = np.asarray([shapley_kernel_weight(M, s) for s in range(M + 1)],
                    np.float64)
    return (np.concatenate([pinned, ranks < s_draw[:, None]]),
            np.concatenate([pinned_w, wt[s_draw]]))


def solve_shap(Z: np.ndarray, y: np.ndarray, w: np.ndarray) -> np.ndarray:
    """Weighted least squares on the coalition design; returns [M+1] with
    phi0 last."""
    coefs, intercept = weighted_least_squares(Z.astype(np.float64), y, w)
    return np.concatenate([coefs, [intercept]])


class _KernelSHAPBase(LocalExplainerBase):
    def _explain_rows(self, make_samples, K_of_row, rows, score_input_builder,
                      row_keys=None):
        """Shared loop: rows -> coalitions -> model scores -> phi vectors.

        Each row's coalition draw comes from ``row_rng(seed, row)`` — keyed
        on the row CONTENT (``row_keys`` overrides the key when ``rows``
        are not hashable payloads themselves), so the same row gets the
        same design on any host/shard/partitioning. Scoring goes per-row
        through ``_score_samples`` (serial reference path) or through the
        rai fused engine (all rows' perturbations in ladder-bucketed
        mega-batches, one executable per rung)."""
        S = self.get("num_samples")
        seed = self.get("seed")
        keys = list(rows) if row_keys is None else list(row_keys)
        designs = []                       # (states [S,K], weights [S])
        for r, key in zip(rows, keys):
            designs.append(sample_coalitions(K_of_row(r), S,
                                             row_rng(seed, key)))
        if self._use_fused():
            from ..rai.fused import fused_block_scores

            blocks = fused_block_scores(
                self, [make_samples(r, st) for r, (st, _) in
                       zip(rows, designs)], score_input_builder)
        else:
            blocks = [self._score_samples(score_input_builder(
                make_samples(r, st))) for r, (st, _) in zip(rows, designs)]
        expl = []
        for (states, w), scores in zip(designs, blocks):
            phis = [solve_shap(states, scores[:, t], w)
                    for t in range(scores.shape[1])]
            expl.append(np.stack(phis))  # [T, K+1]
        return expl


class VectorSHAP(_KernelSHAPBase):
    """(ref ``VectorSHAP.scala``) feature-vector rows; off features are
    replaced by the background mean (or sampled background rows)."""

    feature_name = "explainers"

    input_col = Param("input_col", "feature vector column", default="features")
    background_data = ComplexParam("background_data", "background DataFrame",
                                   default=None)

    def _background(self, df: DataFrame) -> np.ndarray:
        bg = self.get("background_data") or df
        X = np.stack([np.asarray(v, np.float64)
                      for v in bg.collect_column(self.get("input_col"))])
        return X.mean(axis=0)

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        bg = self._background(df)

        def per_part(p):
            X = np.stack([np.asarray(v, np.float64) for v in p[self.get("input_col")]])

            expl = self._explain_rows(
                make_samples=lambda x, states: np.where(states, x[None, :], bg[None, :]),
                K_of_row=lambda x: len(x),
                rows=list(X),
                score_input_builder=lambda samples: DataFrame.from_dict(
                    {self.get("input_col"): samples.astype(np.float32)}),
            )
            q = dict(p)
            q[self.get("output_col")] = self._pack_explanations(expl)
            return q

        return df.map_partitions(per_part)


class TabularSHAP(VectorSHAP):
    """(ref ``TabularSHAP.scala``) named numeric columns."""

    input_cols = ComplexParam("input_cols", "numeric feature columns")

    def _transform(self, df: DataFrame) -> DataFrame:
        cols = self.get("input_cols")
        self.require_columns(df, *cols)
        vec_col = "_shap_features"
        assembled = df.with_column(
            vec_col, lambda p: np.stack([np.asarray(p[c], np.float32) for c in cols], axis=1))
        inner_model = self.get("model")

        class _Unpack:
            def transform(self_inner, sdf: DataFrame) -> DataFrame:
                X = np.asarray(np.stack(list(sdf.collect_column(vec_col))))
                return inner_model.transform(DataFrame.from_dict(
                    {c: X[:, i] for i, c in enumerate(cols)}))

        proxy = self.copy()
        proxy.set(model=_Unpack(), input_col=vec_col)
        if self.get("background_data") is not None:
            bgd = self.get("background_data")
            proxy.set(background_data=bgd.with_column(
                vec_col, lambda p: np.stack([np.asarray(p[c], np.float32) for c in cols], axis=1)))
        out = VectorSHAP._transform(proxy, assembled)
        return out.drop(vec_col)


class ImageSHAP(_KernelSHAPBase):
    """(ref ``ImageSHAP.scala``) superpixels as players; off superpixels
    blanked to the image mean color."""

    feature_name = "explainers"

    input_col = Param("input_col", "image column", default="image")
    cell_size = Param("cell_size", "SLIC seed pitch", default=16.0,
                      converter=TypeConverters.to_float)
    modifier = Param("modifier", "SLIC color weight", default=130.0,
                     converter=TypeConverters.to_float)

    def _transform(self, df: DataFrame) -> DataFrame:
        from ..image.superpixel import slic_segments
        from ..image.transforms import as_image

        self.require_columns(df, self.get("input_col"))

        def per_part(p):
            imgs = [as_image(v) for v in p[self.get("input_col")]]
            rows = []
            for im in imgs:
                labels = slic_segments(im, self.get("cell_size"),
                                       self.get("modifier"))
                rows.append((im, labels, im.mean(axis=(0, 1))))

            def make_samples(row, states):
                im, labels, fill = row
                masks = states[:, labels]                  # [S, H, W]
                return np.where(masks[:, :, :, None], im[None],
                                fill[None, None, None, :])

            expl = self._explain_rows(
                make_samples=make_samples,
                K_of_row=lambda row: int(row[1].max()) + 1,
                rows=rows,
                score_input_builder=lambda samples: DataFrame.from_dict(
                    {self.get("input_col"): [s for s in samples]}),
                row_keys=imgs,
            )
            q = dict(p)
            q[self.get("output_col")] = self._pack_explanations(expl)
            return q

        return df.map_partitions(per_part)


class TextSHAP(_KernelSHAPBase):
    """(ref ``TextSHAP.scala``) tokens as players; off tokens dropped."""

    feature_name = "explainers"

    input_col = Param("input_col", "text column", default="text")
    token_col = Param("token_col", "token list output column", default="tokens")

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))

        def per_part(p):
            texts = [str(t) for t in p[self.get("input_col")]]
            token_rows = np.empty(len(texts), dtype=object)
            for r, text in enumerate(texts):
                token_rows[r] = np.asarray(text.split(), dtype=object)

            def make_samples(text, states):
                tokens = text.split()
                return [" ".join(t for t, on in zip(tokens, st) if on)
                        for st in states]

            expl = self._explain_rows(
                make_samples=make_samples,
                K_of_row=lambda t: max(len(t.split()), 1),
                rows=texts,
                score_input_builder=lambda samples: DataFrame.from_dict(
                    {self.get("input_col"): samples}),
            )
            q = dict(p)
            q[self.get("output_col")] = self._pack_explanations(expl)
            q[self.get("token_col")] = token_rows
            return q

        return df.map_partitions(per_part)
