"""Shared machinery for local explainers (reference ``explainers/LIMEBase.scala``
/ ``KernelSHAPBase.scala`` common structure: sample -> score through the model
-> fit local surrogate per row)."""

from __future__ import annotations

import hashlib

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Transformer

__all__ = ["LocalExplainerBase", "row_rng"]


def row_rng(seed: int, row_key) -> np.random.Generator:
    """One rng per (seed, row) — the determinism contract of the rai plane.

    The stream is derived from a blake2b digest of the row's CONTENT (array
    bytes / utf-8 text), keyed by ``seed``, so the same row draws the same
    coalitions / neighborhoods no matter which host, shard or partition
    explains it, and no matter how many rows came before it in the batch.
    That content-keying is what makes streamed explanation runs resumable
    byte-identically and partition-invariant (ISSUE 20 satellite)."""
    if isinstance(row_key, np.ndarray):
        payload = np.ascontiguousarray(row_key).tobytes()
    elif isinstance(row_key, (bytes, bytearray)):
        payload = bytes(row_key)
    else:
        payload = str(row_key).encode()
    digest = hashlib.blake2b(payload, digest_size=16,
                             key=str(int(seed)).encode()).digest()
    return np.random.default_rng(int.from_bytes(digest, "little"))


class LocalExplainerBase(Transformer):
    """Common params + the one-shot scoring path: ALL samples for a partition
    go through model.transform in a single DataFrame."""

    model = ComplexParam("model", "fitted Transformer to explain")
    target_col = Param("target_col", "model output column holding scores",
                       default="probability")
    target_classes = ComplexParam("target_classes",
                                  "class indices to explain (default [0])",
                                  default=None)
    output_col = Param("output_col", "explanation column", default="explanation")
    num_samples = Param("num_samples", "perturbations per row", default=256,
                        converter=TypeConverters.to_int)
    seed = Param("seed", "rng seed", default=0, converter=TypeConverters.to_int)
    fused = Param("fused", "perturbation scoring path: True = fused "
                  "ladder-bucketed batches through the shared CompiledCache "
                  "(rai plane), False = the serial reference loop, 'auto' = "
                  "fused when the model exposes an array score fn",
                  default="auto")

    def _use_fused(self) -> bool:
        mode = self.get("fused")
        if mode is True or mode is False:
            return bool(mode)
        from ..rai.fused import array_score_fn

        return array_score_fn(self.get("model")) is not None

    def _target_index(self, n_cols: int) -> list[int]:
        """Class indices to explain, clamped into the model's output width —
        the ONE selection rule shared by the serial ``_score_samples`` path
        and the rai fused engine (parity depends on it)."""
        targets = self.get("target_classes") or [0]
        return [t if t < n_cols else n_cols - 1 for t in targets]

    def _score_samples(self, sample_df: DataFrame) -> np.ndarray:
        """Run the wrapped model; returns [n_samples_total, n_targets]."""
        scored = self.get("model").transform(sample_df)
        col = scored.collect_column(self.get("target_col"))
        arr = np.asarray(np.stack([np.atleast_1d(np.asarray(v, np.float64))
                                   for v in col]))
        return arr[:, self._target_index(arr.shape[1])]

    def transform_source(self, source, sink, **opts):
        """Corpus-scale explanation: the scoring plane's reader→compute→
        writer pipeline (exactly-once DONE-gated sinks, resume, quarantine)
        plus the ``synapseml_rai_*`` series — see ``rai/stream.py``."""
        from ..rai.stream import explain_source

        return explain_source(self, source, sink, **opts)

    def _pack_explanations(self, coef_rows: list) -> np.ndarray:
        from ..rai.metrics import rai_measures

        rai_measures()["explanations"].inc(len(coef_rows),
                                           explainer=type(self).__name__)
        out = np.empty(len(coef_rows), dtype=object)
        for i, c in enumerate(coef_rows):
            out[i] = np.asarray(c, np.float32)
        return out
