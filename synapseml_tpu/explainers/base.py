"""Shared machinery for local explainers (reference ``explainers/LIMEBase.scala``
/ ``KernelSHAPBase.scala`` common structure: sample -> score through the model
-> fit local surrogate per row)."""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Transformer

__all__ = ["LocalExplainerBase"]


class LocalExplainerBase(Transformer):
    """Common params + the one-shot scoring path: ALL samples for a partition
    go through model.transform in a single DataFrame."""

    model = ComplexParam("model", "fitted Transformer to explain")
    target_col = Param("target_col", "model output column holding scores",
                       default="probability")
    target_classes = ComplexParam("target_classes",
                                  "class indices to explain (default [0])",
                                  default=None)
    output_col = Param("output_col", "explanation column", default="explanation")
    num_samples = Param("num_samples", "perturbations per row", default=256,
                        converter=TypeConverters.to_int)
    seed = Param("seed", "rng seed", default=0, converter=TypeConverters.to_int)

    def _score_samples(self, sample_df: DataFrame) -> np.ndarray:
        """Run the wrapped model; returns [n_samples_total, n_targets]."""
        scored = self.get("model").transform(sample_df)
        col = scored.collect_column(self.get("target_col"))
        arr = np.asarray(np.stack([np.atleast_1d(np.asarray(v, np.float64))
                                   for v in col]))
        targets = self.get("target_classes") or [0]
        idx = [t if t < arr.shape[1] else arr.shape[1] - 1 for t in targets]
        return arr[:, idx]

    @staticmethod
    def _pack_explanations(coef_rows: list) -> np.ndarray:
        out = np.empty(len(coef_rows), dtype=object)
        for i, c in enumerate(coef_rows):
            out[i] = np.asarray(c, np.float32)
        return out
