"""Missing-data cleaning and type conversion
(reference ``featurize/CleanMissingData.scala:51``, ``DataConversion.scala``)."""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame, _as_column
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model, Transformer

__all__ = ["CleanMissingData", "CleanMissingDataModel", "DataConversion"]


class CleanMissingDataModel(Model):
    input_cols = Param("input_cols", "columns to clean", converter=TypeConverters.to_list)
    output_cols = Param("output_cols", "cleaned output columns", converter=TypeConverters.to_list)
    fill_values = ComplexParam("fill_values", "column -> replacement value")

    def _transform(self, df: DataFrame) -> DataFrame:
        fills = self.get("fill_values")
        out = df
        for src, dst in zip(self.get("input_cols"), self.get("output_cols")):
            self.require_columns(df, src)
            fill = fills[src]

            def repl(p, _src=src, _fill=fill):
                col = np.asarray(p[_src], dtype=np.float64)
                return np.where(np.isnan(col), _fill, col)

            out = out.with_column(dst, repl)
        return out


class CleanMissingData(Estimator):
    """Impute NaNs with mean/median/custom (ref ``CleanMissingData.scala:51``)."""

    input_cols = Param("input_cols", "columns to clean", converter=TypeConverters.to_list)
    output_cols = Param("output_cols", "cleaned output columns (default: in place)",
                        converter=TypeConverters.to_list)
    cleaning_mode = Param("cleaning_mode", "Mean | Median | Custom", default="Mean",
                          validator=lambda v: v in ("Mean", "Median", "Custom"))
    custom_value = Param("custom_value", "replacement for Custom mode",
                         converter=TypeConverters.to_float)

    def _fit(self, df: DataFrame) -> CleanMissingDataModel:
        ins = self.get("input_cols")
        outs = self.get("output_cols") or ins
        self.require_columns(df, *ins)
        mode = self.get("cleaning_mode")
        fills: dict[str, float] = {}
        for c in ins:
            if mode == "Custom":
                fills[c] = float(self.get("custom_value"))
                continue
            col = np.asarray(df.collect_column(c), dtype=np.float64)
            valid = col[~np.isnan(col)]
            if len(valid) == 0:
                fills[c] = 0.0
            elif mode == "Mean":
                fills[c] = float(np.mean(valid))
            else:
                fills[c] = float(np.median(valid))
        return CleanMissingDataModel(input_cols=ins, output_cols=outs, fill_values=fills)


_CONVERTERS = {
    "boolean": lambda c: np.asarray(c).astype(bool),
    "byte": lambda c: np.asarray(c).astype(np.int8),
    "short": lambda c: np.asarray(c).astype(np.int16),
    "integer": lambda c: np.asarray(c).astype(np.int32),
    "long": lambda c: np.asarray(c).astype(np.int64),
    "float": lambda c: np.asarray(c).astype(np.float32),
    "double": lambda c: np.asarray(c).astype(np.float64),
    "string": lambda c: _as_column([str(v) for v in c]),
    "toCategorical": None,  # handled via ValueIndexer
    "clearCategorical": None,
}


class DataConversion(Transformer):
    """Cast columns to a named type (ref ``featurize/DataConversion.scala``);
    date handling reduced to numeric epoch casts."""

    cols = Param("cols", "columns to convert", converter=TypeConverters.to_list)
    convert_to = Param("convert_to", "target type: " + "|".join(k for k in _CONVERTERS),
                       default="double")
    date_time_format = Param("date_time_format", "accepted for parity", default=None)

    def _transform(self, df: DataFrame) -> DataFrame:
        target = self.get("convert_to")
        if target in ("toCategorical", "clearCategorical"):
            from .indexers import ValueIndexer

            out = df
            if target == "toCategorical":
                for c in self.get("cols"):
                    out = ValueIndexer(input_col=c, output_col=c).fit(out).transform(out)
            return out
        conv = _CONVERTERS.get(target)
        if conv is None:
            raise ValueError(f"unknown convert_to {target!r}")
        out = df
        for c in self.get("cols"):
            self.require_columns(df, c)
            out = out.with_column(c, lambda p, _c=c: conv(p[_c]))
        return out
