"""Categorical indexing (reference ``featurize/ValueIndexer.scala:57``,
``IndexToValue.scala``, ``CountSelector.scala``)."""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame, _as_column, scalar_of as _scalar
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model

__all__ = ["ValueIndexer", "ValueIndexerModel", "IndexToValue",
           "CountSelector", "CountSelectorModel"]


class ValueIndexerModel(Model):
    input_col = Param("input_col", "column to index")
    output_col = Param("output_col", "indexed output column")
    levels = ComplexParam("levels", "ordered distinct values; index = position")
    unknown_index = Param("unknown_index", "index for unseen values (-1 errors)",
                          default=-1, converter=TypeConverters.to_int)

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        levels = list(self.get("levels"))
        table = {_scalar(v): i for i, v in enumerate(levels)}
        unk = self.get("unknown_index")

        def per_part(p):
            col = p[self.get("input_col")]
            out = np.empty(len(col), dtype=np.int32)
            for i, v in enumerate(col):
                hit = table.get(_scalar(v), unk)
                if hit < 0:
                    raise ValueError(f"unseen level {v!r} in {self.get('input_col')} "
                                     f"(set unknown_index to tolerate)")
                out[i] = hit
            return out

        return df.with_column(self.get("output_col"), per_part)


class ValueIndexer(Estimator):
    """Learn distinct levels -> contiguous indices (ref ``ValueIndexer.scala:57``).
    Levels sort ascending (numeric) / lexicographic (string) for determinism."""

    input_col = Param("input_col", "column to index")
    output_col = Param("output_col", "indexed output column")
    unknown_index = Param("unknown_index", "index for unseen values at transform",
                          default=-1, converter=TypeConverters.to_int)

    def _fit(self, df: DataFrame) -> ValueIndexerModel:
        col = self.get("input_col")
        self.require_columns(df, col)
        values = df.collect_column(col)
        levels = sorted({_scalar(v) for v in values}, key=lambda v: (str(type(v)), v))
        return ValueIndexerModel(input_col=col,
                                 output_col=self.get("output_col") or f"{col}_indexed",
                                 levels=levels, unknown_index=self.get("unknown_index"))


class IndexToValue(Model):
    """Inverse of ValueIndexerModel (ref ``featurize/IndexToValue.scala``):
    reads levels from a fitted model or explicit list."""

    input_col = Param("input_col", "index column")
    output_col = Param("output_col", "value output column")
    levels = ComplexParam("levels", "ordered distinct values")

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        levels = list(self.get("levels"))

        def per_part(p):
            idx = np.asarray(p[self.get("input_col")], dtype=np.int64)
            return _as_column([levels[i] for i in idx])

        return df.with_column(self.get("output_col"), per_part)


class CountSelectorModel(Model):
    input_col = Param("input_col", "feature matrix column")
    output_col = Param("output_col", "selected output column")
    indices = ComplexParam("indices", "kept feature slot indices")

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        keep = np.asarray(self.get("indices"), dtype=np.int64)
        return df.with_column(
            self.get("output_col"),
            lambda p: np.asarray(np.stack(list(p[self.get("input_col")])), np.float32)[:, keep])


class CountSelector(Estimator):
    """Drop always-zero feature slots (ref ``featurize/CountSelector.scala`` —
    CountBasedFeatureSelector on sparse vectors; here on dense matrix columns)."""

    input_col = Param("input_col", "feature matrix column", default="features")
    output_col = Param("output_col", "selected output column", default="features")

    def _fit(self, df: DataFrame) -> CountSelectorModel:
        col = self.get("input_col")
        self.require_columns(df, col)
        nonzero = None
        for p in df.partitions:
            mat = np.asarray(np.stack(list(p[col])), np.float64)
            counts = (mat != 0).sum(axis=0)
            nonzero = counts if nonzero is None else nonzero + counts
        keep = np.nonzero(nonzero > 0)[0]
        return CountSelectorModel(input_col=col, output_col=self.get("output_col"),
                                  indices=keep)

