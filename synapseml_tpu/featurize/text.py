"""Text featurization (reference ``featurize/text/TextFeaturizer.scala:193``,
``PageSplitter.scala``, ``MultiNGram.scala``).

TextFeaturizer = tokenize -> ngram -> hashing-TF -> IDF, emitting a dense
float32 matrix column sized ``num_features`` (TPU-friendly; the reference emits
SparkML sparse vectors)."""

from __future__ import annotations

import re

import numpy as np

from ..core.dataframe import DataFrame, _as_column
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model, Transformer
from ..vw.hashing import hash_feature

__all__ = ["TextFeaturizer", "TextFeaturizerModel", "PageSplitter", "MultiNGram"]

_TOKEN_RE = re.compile(r"[A-Za-z0-9_]+")

# ASCII-only lowercase so the Python path buckets identically to the native C++
# tokenizer: str.lower() maps e.g. 'K' (Kelvin sign) -> 'k' and can synthesize
# ASCII letters from non-ASCII input, which the C++ path treats as separators.
_ASCII_LOWER = str.maketrans(
    "ABCDEFGHIJKLMNOPQRSTUVWXYZ", "abcdefghijklmnopqrstuvwxyz")


def _tokenize(text: str, lower: bool) -> list[str]:
    s = str(text)
    return _TOKEN_RE.findall(s.translate(_ASCII_LOWER) if lower else s)


def _ngrams(tokens: list[str], n: int) -> list[str]:
    if n <= 1:
        return tokens
    return [" ".join(tokens[i : i + n]) for i in range(len(tokens) - n + 1)]


class TextFeaturizerModel(Model):
    input_col = Param("input_col", "text column")
    output_col = Param("output_col", "feature matrix column")
    num_features = Param("num_features", "hash buckets", default=4096,
                         converter=TypeConverters.to_int)
    n_gram_length = Param("n_gram_length", "ngram size", default=1,
                          converter=TypeConverters.to_int)
    to_lower_case = Param("to_lower_case", "lowercase", default=True,
                          converter=TypeConverters.to_bool)
    binary = Param("binary", "binary TF", default=False, converter=TypeConverters.to_bool)
    idf = ComplexParam("idf", "per-bucket inverse document frequency (None = TF only)")

    def _doc_buckets(self, text) -> list[int]:
        nbits = int(np.log2(self.get("num_features")))
        toks = _ngrams(_tokenize(text, self.get("to_lower_case")), self.get("n_gram_length"))
        return [hash_feature(g, "", nbits) for g in toks]

    def _docs_buckets(self, texts) -> list:
        """Per-doc bucket id arrays; unigram path goes through the native C++
        tokenizer+hasher when built (same tokens, same murmur, same mask)."""
        if self.get("n_gram_length") <= 1:
            from .. import native
            from ..vw.hashing import namespace_seed

            nbits = int(np.log2(self.get("num_features")))
            buckets = native.docs_token_hashes(
                [str(t) for t in texts], seed=namespace_seed(""),
                num_bits=nbits, lower=self.get("to_lower_case"))
            if buckets is not None:
                return buckets
        return [self._doc_buckets(t) for t in texts]

    def _tf(self, texts) -> np.ndarray:
        d = self.get("num_features")
        out = np.zeros((len(texts), d), np.float32)
        for i, buckets in enumerate(self._docs_buckets(texts)):
            for b in buckets:
                out[i, b] += 1.0
        if self.get("binary"):
            out = (out > 0).astype(np.float32)
        return out

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))

        def per_part(p):
            tf = self._tf(list(p[self.get("input_col")]))
            idf = self.get("idf")
            return tf * np.asarray(idf, np.float32) if idf is not None else tf

        return df.with_column(self.get("output_col"), per_part)


class TextFeaturizer(Estimator):
    """(ref ``TextFeaturizer.scala:193``)"""

    input_col = Param("input_col", "text column", default="text")
    output_col = Param("output_col", "feature matrix column", default="features")
    num_features = Param("num_features", "hash buckets (power of two)", default=4096,
                         converter=TypeConverters.to_int,
                         validator=lambda v: v > 0 and (v & (v - 1)) == 0)
    n_gram_length = Param("n_gram_length", "ngram size", default=1,
                          converter=TypeConverters.to_int)
    to_lower_case = Param("to_lower_case", "lowercase", default=True,
                          converter=TypeConverters.to_bool)
    use_idf = Param("use_idf", "apply IDF weighting", default=True,
                    converter=TypeConverters.to_bool)
    min_doc_freq = Param("min_doc_freq", "zero buckets seen in fewer docs", default=1,
                         converter=TypeConverters.to_int)
    binary = Param("binary", "binary TF", default=False, converter=TypeConverters.to_bool)

    def _fit(self, df: DataFrame) -> TextFeaturizerModel:
        self.require_columns(df, self.get("input_col"))
        model = TextFeaturizerModel(
            input_col=self.get("input_col"), output_col=self.get("output_col"),
            num_features=self.get("num_features"), n_gram_length=self.get("n_gram_length"),
            to_lower_case=self.get("to_lower_case"), binary=self.get("binary"), idf=None)
        if self.get("use_idf"):
            texts = list(df.collect_column(self.get("input_col")))
            # streamed per-doc bucket sets: O(num_features) memory, never the
            # dense (n_docs x num_features) TF matrix
            docfreq = np.zeros(self.get("num_features"), np.float64)
            for buckets in model._docs_buckets(texts):
                for b in set(np.asarray(buckets).tolist()):
                    docfreq[b] += 1.0
            n_docs = max(len(texts), 1)
            idf = np.log((n_docs + 1.0) / (docfreq + 1.0))  # SparkML IDF formula
            idf[docfreq < self.get("min_doc_freq")] = 0.0
            model.set(idf=idf.astype(np.float32))
        return model


class PageSplitter(Transformer):
    """Split text into page strings within [min,max] length, preferring word
    boundaries (ref ``featurize/text/PageSplitter.scala``)."""

    input_col = Param("input_col", "text column", default="text")
    output_col = Param("output_col", "pages (list) column", default="pages")
    maximum_page_length = Param("maximum_page_length", "max chars per page", default=5000,
                                converter=TypeConverters.to_int)
    minimum_page_length = Param("minimum_page_length", "min chars before a boundary split",
                                default=4500, converter=TypeConverters.to_int)
    boundary_regex = Param("boundary_regex", "preferred split points", default=r"\s")

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        mx, mn = self.get("maximum_page_length"), self.get("minimum_page_length")
        brx = re.compile(self.get("boundary_regex"))

        def split(text: str) -> list[str]:
            s, pages = str(text), []
            while len(s) > mx:
                cut = None
                for m in brx.finditer(s, max(mn, 1), mx):
                    cut = m.start()
                cut = cut if cut and cut > 0 else mx  # cut=0 would never shrink s
                pages.append(s[:cut])
                s = s[cut:]
            pages.append(s)
            return pages

        def per_part(p):
            return _as_column([split(t) for t in p[self.get("input_col")]])

        return df.with_column(self.get("output_col"), per_part)


class MultiNGram(Transformer):
    """Token lists -> concatenated ngrams of several lengths
    (ref ``featurize/text/MultiNGram.scala``)."""

    input_col = Param("input_col", "token-list column", default="tokens")
    output_col = Param("output_col", "ngram-list column", default="ngrams")
    lengths = Param("lengths", "ngram sizes to include", default=[1, 2, 3],
                    converter=TypeConverters.to_list)

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        lengths = [int(x) for x in self.get("lengths")]

        def per_part(p):
            out = []
            for toks in p[self.get("input_col")]:
                toks = list(toks)
                grams: list[str] = []
                for n in lengths:
                    grams.extend(_ngrams(toks, n))
                out.append(grams)
            return _as_column(out)

        return df.with_column(self.get("output_col"), per_part)
