"""Auto-featurization (reference ``core/.../featurize/``, SURVEY.md §2.5).

Turns heterogeneous DataFrame columns into the dense float32 matrix columns the
TPU estimators consume (``features`` ndarray column), replacing SparkML's
VectorAssembler-based sparse pipeline with direct columnar assembly.
"""

from .clean import CleanMissingData, CleanMissingDataModel, DataConversion  # noqa: F401
from .indexers import (  # noqa: F401
    CountSelector,
    CountSelectorModel,
    IndexToValue,
    ValueIndexer,
    ValueIndexerModel,
)
from .featurize import Featurize, FeaturizeModel  # noqa: F401
from .text import MultiNGram, PageSplitter, TextFeaturizer, TextFeaturizerModel  # noqa: F401
