"""Featurize — auto-assembly of mixed columns into one dense float32 matrix.

Reference ``featurize/Featurize.scala:35``: imputation + one-hot (low-cardinality
strings/categoricals) + hashing (high-cardinality strings) + vector assembly.
TPU-native difference: output is a dense ``(n, d)`` float32 ndarray column
(``features``) that maps straight into HBM, not a SparkML sparse vector.
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame, scalar_of as _scalar
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model
from ..vw.hashing import hash_feature

__all__ = ["Featurize", "FeaturizeModel"]


class FeaturizeModel(Model):
    input_cols = Param("input_cols", "source columns", converter=TypeConverters.to_list)
    output_col = Param("output_col", "assembled matrix column", default="features")
    plan = ComplexParam("plan", "per-column featurization plan")
    num_features = Param("num_features", "hash bucket count (power of two)",
                         validator=lambda v: v > 0 and (v & (v - 1)) == 0,
                         default=262144, converter=TypeConverters.to_int)

    def _transform(self, df: DataFrame) -> DataFrame:
        plan = self.get("plan")
        self.require_columns(df, *self.get("input_cols"))
        nbits = int(np.log2(self.get("num_features")))

        def per_part(p):
            n = len(next(iter(p.values())))
            blocks: list[np.ndarray] = []
            for c in self.get("input_cols"):
                spec = plan[c]
                col = p[c]
                kind = spec["kind"]
                if kind == "numeric":
                    v = np.asarray(col, np.float64)
                    v = np.where(np.isnan(v), spec["fill"], v)
                    blocks.append(v[:, None].astype(np.float32))
                elif kind == "matrix":
                    mat = np.asarray(np.stack(list(col)), np.float32)
                    mat = np.where(np.isnan(mat), 0.0, mat)
                    blocks.append(mat.reshape(n, -1))
                elif kind == "onehot":
                    levels = {v: i for i, v in enumerate(spec["levels"])}
                    out = np.zeros((n, len(levels)), np.float32)
                    for i, v in enumerate(col):
                        j = levels.get(_scalar(v))
                        if j is not None:
                            out[i, j] = 1.0
                    blocks.append(out)
                elif kind == "hash":
                    out = np.zeros((n, self.get("num_features")), np.float32)
                    for i, v in enumerate(col):
                        for tok in str(v).split():
                            out[i, hash_feature(tok, c, nbits)] += 1.0
                    blocks.append(out)
                else:  # pragma: no cover
                    raise ValueError(f"unknown plan kind {kind}")
            return np.concatenate(blocks, axis=1) if blocks else np.zeros((n, 0), np.float32)

        return df.with_column(self.get("output_col"), per_part)

    @property
    def feature_dim(self) -> int:
        plan = self.get("plan")
        d = 0
        for c in self.get("input_cols"):
            spec = plan[c]
            d += {"numeric": 1, "matrix": spec.get("dim", 0),
                  "onehot": len(spec.get("levels", [])),
                  "hash": self.get("num_features")}[spec["kind"]]
        return d


class Featurize(Estimator):
    """Auto-featurization estimator (ref ``Featurize.scala:35``)."""

    input_cols = Param("input_cols", "source columns", converter=TypeConverters.to_list)
    output_col = Param("output_col", "assembled matrix column", default="features")
    one_hot_encode_categoricals = Param("one_hot_encode_categoricals",
                                        "one-hot low-cardinality strings", default=True,
                                        converter=TypeConverters.to_bool)
    num_features = Param("num_features", "hash buckets for high-cardinality strings "
                         "(power of two)", default=256, converter=TypeConverters.to_int,
                         validator=lambda v: v > 0 and (v & (v - 1)) == 0)
    impute_missing = Param("impute_missing", "impute numeric NaNs with the mean",
                           default=True, converter=TypeConverters.to_bool)
    max_one_hot_cardinality = Param("max_one_hot_cardinality",
                                    "string cardinality cutoff for one-hot vs hashing",
                                    default=64, converter=TypeConverters.to_int)

    def _fit(self, df: DataFrame) -> FeaturizeModel:
        cols = self.get("input_cols")
        self.require_columns(df, *cols)
        plan: dict[str, dict] = {}
        for c in cols:
            sample = df.collect_column(c)
            if sample.dtype != object and sample.ndim > 1:
                plan[c] = {"kind": "matrix", "dim": int(np.prod(sample.shape[1:]))}
            elif sample.dtype != object and np.issubdtype(sample.dtype, np.number):
                vals = sample.astype(np.float64)
                valid = vals[~np.isnan(vals)]
                fill = float(np.mean(valid)) if self.get("impute_missing") and len(valid) else 0.0
                plan[c] = {"kind": "numeric", "fill": fill}
            else:
                first = next((v for v in sample if v is not None), None)
                if isinstance(first, (list, tuple, np.ndarray)):
                    plan[c] = {"kind": "matrix", "dim": len(first)}
                else:
                    levels = sorted({_scalar(v) for v in sample}, key=str)
                    if (self.get("one_hot_encode_categoricals")
                            and len(levels) <= self.get("max_one_hot_cardinality")):
                        plan[c] = {"kind": "onehot", "levels": levels}
                    else:
                        plan[c] = {"kind": "hash"}
        return FeaturizeModel(input_cols=cols, output_col=self.get("output_col"),
                              plan=plan, num_features=self.get("num_features"))

