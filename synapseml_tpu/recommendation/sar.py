"""SAR — Smart Adaptive Recommendations (reference ``recommendation/SAR.scala:36``
/ ``SARModel.scala:23``).

Semantics kept from the reference: an item-item co-occurrence similarity
matrix (jaccard | lift | cooccurrence) and a time-decayed user-item affinity
matrix (half-life decay of interaction recency); recommendation score is
affinity @ similarity with seen items optionally masked out.

TPU shape: both matrices are dense [I, I] / [U, I] arrays; scoring + top-k is
one jitted matmul batch per user block (MXU) instead of the reference's Spark
joins over sparse blocks.
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model

__all__ = ["SAR", "SARModel"]


class SAR(Estimator):
    feature_name = "recommendation"

    user_col = Param("user_col", "indexed user column", default="user_idx")
    item_col = Param("item_col", "indexed item column", default="item_idx")
    rating_col = Param("rating_col", "rating/weight column (None = implicit 1.0)",
                       default=None)
    time_col = Param("time_col", "interaction timestamp column (None = no decay)",
                     default=None)
    similarity_function = Param("similarity_function",
                                "jaccard | lift | cooccurrence",
                                default="jaccard",
                                validator=lambda v: v in ("jaccard", "lift", "cooccurrence"))
    support_threshold = Param("support_threshold",
                              "min co-occurrence count kept in the similarity",
                              default=4, converter=TypeConverters.to_int)
    time_decay_coeff = Param("time_decay_coeff", "half-life in days for affinity decay",
                             default=30, converter=TypeConverters.to_int)

    def _fit(self, df: DataFrame) -> "SARModel":
        self.require_columns(df, self.get("user_col"), self.get("item_col"))
        # fail fast on typos: a user-set column name must exist (None = off)
        if self.get("rating_col"):
            self.require_columns(df, self.get("rating_col"))
        if self.get("time_col"):
            self.require_columns(df, self.get("time_col"))
        users = np.asarray(df.collect_column(self.get("user_col")), np.int64)
        items = np.asarray(df.collect_column(self.get("item_col")), np.int64)
        n_users = int(users.max()) + 1 if len(users) else 0
        n_items = int(items.max()) + 1 if len(items) else 0

        ratings = (np.asarray(df.collect_column(self.get("rating_col")), np.float64)
                   if self.get("rating_col") else np.ones(len(users)))

        # ---- affinity: sum of ratings with half-life time decay ----
        if self.get("time_col"):
            t = np.asarray(df.collect_column(self.get("time_col")), np.float64)
            t_ref = t.max() if len(t) else 0.0
            half_life_s = self.get("time_decay_coeff") * 86400.0
            weights = ratings * np.power(2.0, -(t_ref - t) / half_life_s)
        else:
            weights = ratings
        affinity = np.zeros((n_users, n_items), np.float32)
        np.add.at(affinity, (users, items), weights)

        # ---- item-item similarity from binarized co-occurrence ----
        seen = np.zeros((n_users, n_items), np.float32)
        seen[users, items] = 1.0
        cooc = seen.T @ seen                              # [I, I] co-occurrence
        thresh = self.get("support_threshold")
        cooc = np.where(cooc >= thresh, cooc, 0.0)
        diag = np.diag(cooc).copy()
        fn = self.get("similarity_function")
        if fn == "cooccurrence":
            sim = cooc
        elif fn == "jaccard":
            denom = diag[:, None] + diag[None, :] - cooc
            sim = np.divide(cooc, denom, out=np.zeros_like(cooc), where=denom > 0)
        else:  # lift
            denom = diag[:, None] * diag[None, :]
            sim = np.divide(cooc, denom, out=np.zeros_like(cooc), where=denom > 0)
        return SARModel(user_data_frame=affinity.astype(np.float32),
                        item_data_frame=sim.astype(np.float32),
                        seen_items=seen.astype(bool),
                        user_col=self.get("user_col"),
                        item_col=self.get("item_col"))


class SARModel(Model):
    """(ref ``SARModel.scala:23``) — ``recommend_for_all_users(k)`` and
    transform (adds per-row recommendations for the user column)."""

    user_data_frame = ComplexParam("user_data_frame", "[U, I] time-decayed affinity")
    item_data_frame = ComplexParam("item_data_frame", "[I, I] item similarity")
    seen_items = ComplexParam("seen_items", "[U, I] bool seen mask")
    user_col = Param("user_col", "indexed user column", default="user_idx")
    item_col = Param("item_col", "indexed item column", default="item_idx")
    output_col = Param("output_col", "recommendations column", default="recommendations")
    k = Param("k", "recommendations per user in transform", default=10,
              converter=TypeConverters.to_int)
    remove_seen = Param("remove_seen", "mask already-seen items", default=True,
                        converter=TypeConverters.to_bool)

    def _scores_fn(self):
        import jax
        import jax.numpy as jnp

        if self.__dict__.get("_cache_jitted") is None:
            sim = jnp.asarray(self.get("item_data_frame"))

            def fn(aff_block, seen_block, k):
                scores = aff_block @ sim                 # [B, I] on the MXU
                scores = jnp.where(seen_block, -jnp.inf, scores)
                vals, idx = jax.lax.top_k(scores, k)
                return vals, idx

            self.__dict__["_cache_jitted"] = jax.jit(fn, static_argnums=2)
        return self.__dict__["_cache_jitted"]

    def recommend_for_all_users(self, k: int, batch: int = 512) -> DataFrame:
        aff = np.asarray(self.get("user_data_frame"))
        seen = np.asarray(self.get("seen_items"))
        if not self.get("remove_seen"):
            seen = np.zeros_like(seen)
        fn = self._scores_fn()
        U = aff.shape[0]
        k = min(k, aff.shape[1])
        users, recs, ratings = [], [], []
        for s in range(0, U, batch):
            e = min(s + batch, U)
            pad = batch - (e - s)
            vals, idx = fn(np.pad(aff[s:e], ((0, pad), (0, 0))),
                           np.pad(seen[s:e], ((0, pad), (0, 0))), k)
            vals, idx = np.asarray(vals)[: e - s], np.asarray(idx)[: e - s]
            for i in range(e - s):
                keep = np.isfinite(vals[i])  # drop masked (seen) top_k fills
                users.append(s + i)
                recs.append(idx[i][keep].astype(np.int32))
                ratings.append(vals[i][keep].astype(np.float32))
        rec_col = np.empty(len(recs), dtype=object)
        rat_col = np.empty(len(ratings), dtype=object)
        rec_col[:] = recs
        rat_col[:] = ratings
        return DataFrame.from_dict({
            self.get("user_col"): np.asarray(users, np.int32),
            "recommendations": rec_col,
            "ratings": rat_col,
        })

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("user_col"))
        all_recs = self.recommend_for_all_users(k=self.get("k"))
        rec_of = dict(zip(all_recs.collect_column(self.get("user_col")).tolist(),
                          list(all_recs.collect_column("recommendations"))))

        def per_part(p):
            out = np.empty(len(p[self.get("user_col")]), dtype=object)
            for i, u in enumerate(p[self.get("user_col")]):
                out[i] = rec_of.get(int(u), np.empty(0, np.int32))
            return out

        return df.with_column(self.get("output_col"), per_part)
