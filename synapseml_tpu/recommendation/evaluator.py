"""RankingEvaluator (reference ``RankingEvaluator.scala`` /
``RecommendationHelper.scala``): NDCG@k, MAP@k, precision@k, recall@k over
(prediction list, ground-truth list) rows."""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param, TypeConverters
from ..core.pipeline import Transformer

__all__ = ["RankingEvaluator", "ndcg_at_k", "map_at_k", "precision_at_k", "recall_at_k"]


def _as_list(v):
    vals = list(np.asarray(v).ravel())
    seen, out = set(), []
    for x in vals:  # dedupe, keeping rank order: duplicates must not double-count
        if x not in seen:
            seen.add(x)
            out.append(x)
    return out


def ndcg_at_k(pred, truth, k: int) -> float:
    pred, truth = _as_list(pred)[:k], set(_as_list(truth))
    if not truth:
        return 0.0
    dcg = sum(1.0 / np.log2(i + 2) for i, p in enumerate(pred) if p in truth)
    idcg = sum(1.0 / np.log2(i + 2) for i in range(min(len(truth), k)))
    return float(dcg / idcg) if idcg > 0 else 0.0


def map_at_k(pred, truth, k: int) -> float:
    pred, truth = _as_list(pred)[:k], set(_as_list(truth))
    if not truth:
        return 0.0
    hits, score = 0, 0.0
    for i, p in enumerate(pred):
        if p in truth:
            hits += 1
            score += hits / (i + 1)
    return float(score / min(len(truth), k))


def precision_at_k(pred, truth, k: int) -> float:
    pred, truth = _as_list(pred)[:k], set(_as_list(truth))
    return float(len([p for p in pred if p in truth]) / k) if k else 0.0


def recall_at_k(pred, truth, k: int) -> float:
    pred, truth = _as_list(pred)[:k], set(_as_list(truth))
    if not truth:
        return 0.0
    return float(len([p for p in pred if p in truth]) / len(truth))


_METRICS = {"ndcgAt": ndcg_at_k, "map": map_at_k,
            "precisionAtk": precision_at_k, "recallAtK": recall_at_k}


class RankingEvaluator(Transformer):
    """Consumes a DataFrame with per-user prediction and ground-truth item
    lists; emits a one-row metrics DataFrame (all metrics) — SparkML evaluators
    return a scalar via ``evaluate``, kept here too."""

    feature_name = "recommendation"

    prediction_col = Param("prediction_col", "ranked predicted item list column",
                           default="prediction")
    label_col = Param("label_col", "ground-truth item list column", default="label")
    k = Param("k", "cutoff", default=10, converter=TypeConverters.to_int)
    metric_name = Param("metric_name", "ndcgAt | map | precisionAtk | recallAtK",
                        default="ndcgAt", validator=lambda v: v in _METRICS)

    def evaluate(self, df: DataFrame) -> float:
        self.require_columns(df, self.get("prediction_col"), self.get("label_col"))
        fn = _METRICS[self.get("metric_name")]
        preds = df.collect_column(self.get("prediction_col"))
        labels = df.collect_column(self.get("label_col"))
        k = self.get("k")
        vals = [fn(p, t, k) for p, t in zip(preds, labels)]
        return float(np.mean(vals)) if vals else 0.0

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("prediction_col"), self.get("label_col"))
        preds = df.collect_column(self.get("prediction_col"))
        labels = df.collect_column(self.get("label_col"))
        k = self.get("k")
        row = {name: np.asarray([np.mean([fn(p, t, k) for p, t in zip(preds, labels)])
                                 if len(preds) else 0.0])
               for name, fn in _METRICS.items()}
        return DataFrame([row])
