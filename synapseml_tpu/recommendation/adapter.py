"""RankingAdapter + RankingTrainValidationSplit (reference
``RankingAdapter.scala:19``, ``RankingTrainValidationSplit.scala:25``).

RankingAdapter fits any recommender and reshapes its output into the
(per-user predicted list, per-user ground-truth list) rows RankingEvaluator
consumes. RankingTrainValidationSplit does a stratified-by-user temporal/random
split and sweeps estimator param maps, keeping the best by ranking metric.
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model
from .evaluator import RankingEvaluator

__all__ = ["RankingAdapter", "RankingAdapterModel", "RankingTrainValidationSplit"]


def _group_items(users: np.ndarray, items: np.ndarray) -> dict:
    out: dict = {}
    for u, i in zip(users.tolist(), items.tolist()):
        out.setdefault(u, []).append(i)
    return out


class RankingAdapter(Estimator):
    feature_name = "recommendation"

    recommender = ComplexParam("recommender", "estimator producing a recommender model")
    k = Param("k", "recommendations per user", default=10, converter=TypeConverters.to_int)
    user_col = Param("user_col", "indexed user column", default="user_idx")
    item_col = Param("item_col", "indexed item column", default="item_idx")

    def _fit(self, df: DataFrame) -> "RankingAdapterModel":
        model = self.get("recommender").fit(df)
        return RankingAdapterModel(recommender_model=model, k=self.get("k"),
                                   user_col=self.get("user_col"),
                                   item_col=self.get("item_col"))


class RankingAdapterModel(Model):
    recommender_model = ComplexParam("recommender_model", "fitted recommender")
    k = Param("k", "recommendations per user", default=10, converter=TypeConverters.to_int)
    user_col = Param("user_col", "indexed user column", default="user_idx")
    item_col = Param("item_col", "indexed item column", default="item_idx")

    def _transform(self, df: DataFrame) -> DataFrame:
        """df = held-out interactions; emits one row per user:
        prediction (ranked recs) + label (true items)."""
        self.require_columns(df, self.get("user_col"), self.get("item_col"))
        model = self.get("recommender_model")
        recs = model.recommend_for_all_users(self.get("k"))
        rec_of = dict(zip(recs.collect_column(self.get("user_col")).tolist(),
                          list(recs.collect_column("recommendations"))))
        truth = _group_items(np.asarray(df.collect_column(self.get("user_col"))),
                             np.asarray(df.collect_column(self.get("item_col"))))
        users = sorted(truth)
        pred_col = np.empty(len(users), dtype=object)
        label_col = np.empty(len(users), dtype=object)
        for n, u in enumerate(users):
            pred_col[n] = np.asarray(rec_of.get(u, []), np.int32)
            label_col[n] = np.asarray(truth[u], np.int32)
        return DataFrame.from_dict({self.get("user_col"): np.asarray(users),
                                    "prediction": pred_col, "label": label_col})


class RankingTrainValidationSplit(Estimator):
    """(ref ``RankingTrainValidationSplit.scala:25``) — per-user holdout split +
    param sweep scored by a ranking metric."""

    feature_name = "recommendation"

    estimator = ComplexParam("estimator", "recommender estimator to sweep")
    estimator_param_maps = ComplexParam("estimator_param_maps",
                                        "list of param dicts (empty = single fit)",
                                        default=None)
    evaluator = ComplexParam("evaluator", "RankingEvaluator", default=None)
    train_ratio = Param("train_ratio", "per-user train fraction", default=0.75,
                        converter=TypeConverters.to_float)
    user_col = Param("user_col", "indexed user column", default="user_idx")
    item_col = Param("item_col", "indexed item column", default="item_idx")
    seed = Param("seed", "split seed", default=0, converter=TypeConverters.to_int)

    def split_per_user(self, df: DataFrame) -> tuple[DataFrame, DataFrame]:
        users = np.asarray(df.collect_column(self.get("user_col")))
        rs = np.random.default_rng(self.get("seed"))
        ratio = self.get("train_ratio")
        train_mask = np.zeros(len(users), bool)
        for u in np.unique(users):
            idx = np.nonzero(users == u)[0]
            perm = rs.permutation(len(idx))
            n_train = max(int(round(len(idx) * ratio)), 1)
            train_mask[idx[perm[:n_train]]] = True
        whole = df.collect()
        train = DataFrame([{k: v[train_mask] for k, v in whole.items()}])
        test = DataFrame([{k: v[~train_mask] for k, v in whole.items()}])
        return train, test

    def _fit(self, df: DataFrame) -> "RankingTrainValidationSplitModel":
        self.require_columns(df, self.get("user_col"), self.get("item_col"))
        train, test = self.split_per_user(df)
        evaluator = self.get("evaluator") or RankingEvaluator()
        maps = self.get("estimator_param_maps") or [{}]
        results = []
        for params in maps:
            est = self.get("estimator").copy(params if params else None)
            adapter = RankingAdapter(recommender=est, k=evaluator.get("k"),
                                     user_col=self.get("user_col"),
                                     item_col=self.get("item_col"))
            model = adapter.fit(train)
            metric = evaluator.evaluate(model.transform(test))
            results.append((params, metric, model))
        best = max(results, key=lambda r: r[1])
        return RankingTrainValidationSplitModel(
            best_model=best[2], validation_metrics=[r[1] for r in results])


class RankingTrainValidationSplitModel(Model):
    best_model = ComplexParam("best_model", "winning RankingAdapterModel")
    validation_metrics = ComplexParam("validation_metrics", "metric per param map")

    def _transform(self, df: DataFrame) -> DataFrame:
        return self.get("best_model").transform(df)

    def recommend_for_all_users(self, k: int) -> DataFrame:
        return self.get("best_model").get("recommender_model").recommend_for_all_users(k)
