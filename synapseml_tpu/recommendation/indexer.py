"""RecommendationIndexer (reference ``RecommendationIndexer.scala``):
string/arbitrary user+item ids -> contiguous integer indices (and back)."""

from __future__ import annotations

import os

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param
from ..core.pipeline import Estimator, Model

__all__ = ["RecommendationIndexer", "RecommendationIndexerModel",
           "export_item_index"]


def export_item_index(model, index_dir: str, *, indexer=None,
                      shard_name: str = "items-00000",
                      normalize: bool = False):
    """Materialize a fitted recommender's item-embedding table as a
    retrieval :class:`~synapseml_tpu.retrieval.shards.IndexShard`, making
    "similar items" queries servable on the retrieval plane (fan-out,
    registry versioning, partial degradation) instead of a bespoke path.

    ``model`` is any stage exposing an ``item_data_frame`` complex param
    (SARModel: the [I, I] item-item similarity matrix — row i IS item i's
    embedding in similarity space). ``indexer`` (an optional fitted
    :class:`RecommendationIndexerModel`) recovers raw item ids into the
    shard's payload sidecar. Returns the committed shard."""
    from ..retrieval.shards import write_shard

    table = np.ascontiguousarray(model.get("item_data_frame"), np.float32)
    if table.ndim != 2:
        raise ValueError(f"item_data_frame must be 2-D, got {table.shape}")
    if normalize:
        table = table / np.maximum(
            np.linalg.norm(table, axis=1, keepdims=True), 1e-9)
    n = table.shape[0]
    payloads = None
    if indexer is not None:
        raw = indexer.recover_item(np.arange(n))
        payloads = [{"item": it.item() if hasattr(it, "item") else it}
                    for it in raw]
    return write_shard(os.path.join(index_dir, "shards"), shard_name,
                       table, ids=np.arange(n, dtype=np.int64),
                       payloads=payloads, kind="base")


class RecommendationIndexer(Estimator):
    feature_name = "recommendation"

    user_input_col = Param("user_input_col", "raw user id column", default="user")
    item_input_col = Param("item_input_col", "raw item id column", default="item")
    user_output_col = Param("user_output_col", "indexed user column", default="user_idx")
    item_output_col = Param("item_output_col", "indexed item column", default="item_idx")

    def _fit(self, df: DataFrame) -> "RecommendationIndexerModel":
        self.require_columns(df, self.get("user_input_col"), self.get("item_input_col"))
        users = np.unique(np.asarray(df.collect_column(self.get("user_input_col"))))
        items = np.unique(np.asarray(df.collect_column(self.get("item_input_col"))))
        return RecommendationIndexerModel(
            user_levels=users, item_levels=items,
            user_input_col=self.get("user_input_col"),
            item_input_col=self.get("item_input_col"),
            user_output_col=self.get("user_output_col"),
            item_output_col=self.get("item_output_col"))


class RecommendationIndexerModel(Model):
    user_levels = ComplexParam("user_levels", "sorted unique user ids")
    item_levels = ComplexParam("item_levels", "sorted unique item ids")
    user_input_col = Param("user_input_col", "raw user id column", default="user")
    item_input_col = Param("item_input_col", "raw item id column", default="item")
    user_output_col = Param("user_output_col", "indexed user column", default="user_idx")
    item_output_col = Param("item_output_col", "indexed item column", default="item_idx")

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("user_input_col"), self.get("item_input_col"))
        ul = np.asarray(self.get("user_levels"))
        il = np.asarray(self.get("item_levels"))

        def index_col(levels, col):
            def fn(p):
                vals = np.asarray(p[col])
                idx = np.searchsorted(levels, vals)
                idx = np.clip(idx, 0, len(levels) - 1)
                missing = levels[idx] != vals
                if np.any(missing):
                    raise ValueError(f"unseen ids in column {col}: "
                                     f"{np.asarray(vals)[missing][:5].tolist()}")
                return idx.astype(np.int32)
            return fn

        return (df.with_column(self.get("user_output_col"),
                               index_col(ul, self.get("user_input_col")))
                  .with_column(self.get("item_output_col"),
                               index_col(il, self.get("item_input_col"))))

    def recover_user(self, idx: np.ndarray) -> np.ndarray:
        return np.asarray(self.get("user_levels"))[np.asarray(idx, int)]

    def recover_item(self, idx: np.ndarray) -> np.ndarray:
        return np.asarray(self.get("item_levels"))[np.asarray(idx, int)]
