"""Recommendation toolkit (reference ``core/.../recommendation/`` — SURVEY.md
§2.5): SAR item-item recommender with time-decayed affinity, id indexing,
ranking metrics and train/validation split.

TPU design: SAR's score = userAffinity @ itemSimilarity is a single [U, I] x
[I, I] matmul; both matrices are built with vectorized bincount-style numpy on
the host and scored via a jitted top_k per user batch.
"""

from .indexer import (RecommendationIndexer, RecommendationIndexerModel,
                      export_item_index)
from .sar import SAR, SARModel
from .evaluator import RankingEvaluator
from .adapter import RankingAdapter, RankingTrainValidationSplit

__all__ = ["SAR", "SARModel", "RecommendationIndexer", "RecommendationIndexerModel",
           "RankingEvaluator", "RankingAdapter", "RankingTrainValidationSplit",
           "export_item_index"]
