"""TpuBooster — boosting orchestration, prediction, persistence.

Reference analog: ``booster/LightGBMBooster.scala`` (create/train-iteration/
score/predictLeaf/saveNativeModel lifecycle over the SWIG C API) plus the
training loop of ``TrainUtils.scala:16-222`` (iteration loop, early stopping,
learning-rate delegate). TPU redesign: the booster holds stacked heap-layout
tree arrays; training keeps binned data + running scores resident on device
(optionally sharded over the mesh ``data`` axis — GSPMD inserts the histogram
allreduce that LightGBM's socket ring performed), and prediction is one jitted
scan over trees.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import batching as cb
from .binning import BinMapper
from . import objectives as obj
from . import trees as T

__all__ = ["TpuBooster", "train_booster", "train_booster_from_source",
           "train_boosters_fused"]


def train_boosters_fused(features, labels, trials, **kwargs) -> list:
    """Horizontally fused hyperparameter sweep: N scalar-hyperparameter
    trials (same binning, same effective depth) train inside ONE jitted
    boosting iteration — one fused histogram build per level serves every
    trial, and the executable is shared across arbitrary hyperparameter
    values through the process-wide CompiledCache. Returns one
    :class:`TpuBooster` per trial; see :mod:`synapseml_tpu.gbdt.fused` for
    the fusability rules and :mod:`synapseml_tpu.automl.tune` for the
    sweep-level entry point."""
    from .fused import fused_train_boosters

    return fused_train_boosters(features, labels, trials, **kwargs)


def train_booster_from_source(source, **kwargs) -> "TpuBooster":
    """Out-of-core training: histograms built from a streamed
    :class:`synapseml_tpu.data.ShardedSource` in fixed-memory passes —
    the entry point for datasets that do not fit in host RAM. All batch
    consumption goes through the data plane (``source.iter_shards`` +
    the binned spill); see :mod:`synapseml_tpu.gbdt.streaming` for the
    pass structure and the supported parameter surface."""
    from .streaming import train_booster_streamed

    return train_booster_streamed(source, **kwargs)


class TpuBooster:
    """A trained forest. Arrays are host numpy; jitted predictors are built
    lazily and cached per (batch-shape bucket)."""

    def __init__(self, feature: np.ndarray, threshold_value: np.ndarray,
                 leaf_value: np.ndarray, gain: np.ndarray, *, max_depth: int,
                 num_model_out: int, objective: str, init_score: np.ndarray,
                 num_features: int, params: dict | None = None,
                 best_iteration: int | None = None,
                 cover: np.ndarray | None = None,
                 average_output: bool = False,
                 cat_mask: np.ndarray | None = None,
                 categorical_features: tuple = ()):
        # stacked (num_iters, K, M)
        self.feature = feature
        self.threshold_value = threshold_value
        self.leaf_value = leaf_value
        self.gain = gain
        self.cover = cover
        self.max_depth = int(max_depth)
        self.num_model_out = int(num_model_out)
        self.objective = objective
        self.init_score = np.asarray(init_score, dtype=np.float32)
        self.num_features = int(num_features)
        self.params = dict(params or {})
        self.best_iteration = best_iteration
        self.average_output = bool(average_output)  # rf mode: mean over trees
        # (T, K, M, B) uint8 left-membership of categorical splits, or None
        self.cat_mask = cat_mask
        self.categorical_features = tuple(categorical_features or ())
        self._predict_cache: dict[Any, Callable] = {}

    @property
    def num_iterations(self) -> int:
        return self.feature.shape[0]

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_predict_cache"] = {}  # jitted closures are not picklable
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._predict_cache = {}

    # ---------------- prediction ----------------
    def _make_raw(self, num_iters: int) -> Callable:
        """The traceable raw-margin forest function for ``num_iters``
        trees (closure over device-resident tree tensors)."""
        feat = jnp.asarray(self.feature[:num_iters])
        thr = jnp.asarray(self.threshold_value[:num_iters])
        val = jnp.asarray(self.leaf_value[:num_iters])
        cm = (None if self.cat_mask is None
              else jnp.asarray(self.cat_mask[:num_iters]))
        init = jnp.asarray(self.init_score)
        depth = self.max_depth
        K = self.num_model_out

        avg = 1.0 / num_iters if self.average_output else 1.0

        def raw(x):
            outs = [T.predict_raw_forest(
                x, feat[:, k], thr[:, k], val[:, k], depth,
                cat_masks=None if cm is None else cm[:, k])
                    for k in range(K)]
            return jnp.stack(outs, axis=1) * avg + init[None, :]

        return raw

    def _raw_fn(self, num_iters: int, bucket: int | None,
                scored: bool = False) -> Callable:
        """Scoring executable per (iteration count, row bucket). Ladder
        buckets go through the process-wide CompiledCache (serving-sized
        request streams reuse ladder-many compiled forests instead of
        retracing per batch size); ``bucket=None`` (beyond-ladder offline
        scans) keeps ONE shape-polymorphic jit in the per-instance
        ``_predict_cache`` — arbitrary large batch sizes must not churn the
        shared LRU and evict other stages' warmed serving executables.
        ``scored=True`` fuses the objective transform into the SAME
        program, returning ``(raw, prob)`` in one dispatch + one transfer —
        the classifier serving/bulk-scoring hot path."""
        def build():
            raw = self._make_raw(num_iters)
            if not scored:
                return jax.jit(raw)
            o = obj.get_objective(self.objective,
                                  num_class=self.num_model_out)

            def raw_and_prob(x):
                r = raw(x)
                return r, o.transform(r)

            return jax.jit(raw_and_prob)

        if bucket is None:
            key = ("scored" if scored else "raw", num_iters)
            if key not in self._predict_cache:
                self._predict_cache[key] = build()
            return self._predict_cache[key]
        return cb.get_compiled_cache().get(
            "gbdt_predict_scored" if scored else "gbdt_predict",
            (num_iters, bucket, self.num_features), build,
            instance=cb.instance_token(self), dtype="float32")

    def _dispatch_score(self, features: np.ndarray,
                        num_iterations: int | None, scored: bool) -> tuple:
        """The ONE cast/clamp/bucket/pad/unpad dispatch both scoring entry
        points share. Serving-sized batches pad up to the bucket ladder
        (bounded compiles under a variable request stream); batches past
        the ladder keep their exact shape — a 1M-row training scan must not
        pad toward the next pow-2."""
        x = np.asarray(features, dtype=np.float32)
        n_it = num_iterations or self.best_iteration or self.num_iterations
        n_it = min(n_it, self.num_iterations)
        n = x.shape[0]
        bucketer = cb.default_bucketer()
        if n > bucketer.max_bucket:
            bucket, padded = None, x
        else:
            bucket = bucketer.bucket_for(n)
            padded = cb.pad_rows(x, bucket)
        out = self._raw_fn(n_it, bucket, scored=scored)(jnp.asarray(padded))
        outs = out if isinstance(out, tuple) else (out,)
        return tuple(cb.unpad_rows(np.asarray(o), n) for o in outs)

    def raw_score(self, features: np.ndarray, num_iterations: int | None = None) -> np.ndarray:
        """(N, K) raw margin scores (see ``_dispatch_score`` for the
        bucket-ladder discipline)."""
        return self._dispatch_score(features, num_iterations, scored=False)[0]

    def predict(self, features: np.ndarray, num_iterations: int | None = None) -> np.ndarray:
        """Objective-transformed predictions: probabilities for binary
        (N,), softmax (N, K) for multiclass, raw values for regression."""
        return self.raw_score_and_predict(features, num_iterations)[1]

    def raw_score_and_predict(self, features: np.ndarray,
                              num_iterations: int | None = None
                              ) -> tuple[np.ndarray, np.ndarray]:
        """``(raw margins, objective-transformed predictions)`` from ONE
        fused executable — one forest traversal, one dispatch, one
        device→host transfer. The classifier transform (every
        serving/bulk-scoring batch) needs both; calling ``raw_score`` then
        ``predict`` walked the forest twice."""
        raw, prob = self._dispatch_score(features, num_iterations,
                                         scored=True)
        return raw, prob

    def predict_contrib(self, features: np.ndarray) -> np.ndarray:
        """(N, K, F+1) exact TreeSHAP contributions + bias column (reference
        ``LightGBMBooster.featuresShap``, ``booster/LightGBMBooster.scala:418``).
        Additivity: ``contrib.sum(-1) == raw_score``."""
        if self.cover is None:
            raise ValueError("this booster has no per-node cover statistics "
                             "(trained before TreeSHAP support); retrain to "
                             "enable predict_contrib")
        from .shap import forest_shap

        n_it = self.best_iteration or self.num_iterations
        contrib = forest_shap(self.feature[:n_it], self.threshold_value[:n_it],
                              self.leaf_value[:n_it], self.cover[:n_it],
                              np.zeros_like(self.init_score),
                              np.asarray(features, np.float64),
                              cat_mask=None if self.cat_mask is None
                              else self.cat_mask[:n_it])
        if self.average_output:  # rf: raw = init + mean(trees)
            contrib = contrib / n_it
        contrib[:, :, -1] += np.asarray(self.init_score, np.float64)
        return contrib

    def predict_leaf(self, features: np.ndarray,
                     num_iterations: int | None = None) -> np.ndarray:
        """(N, T*K) per-tree leaf node index (reference ``predictLeaf``).
        Like ``raw_score``, truncates to ``best_iteration`` by default
        (LightGBM's ``pred_leaf`` uses the best iteration too)."""
        x = jnp.asarray(np.asarray(features, dtype=np.float32))
        n_it = num_iterations or self.best_iteration or self.num_iterations
        n_it = min(n_it, self.num_iterations)
        t, k, m = self.feature[:n_it].shape
        feat = jnp.asarray(self.feature[:n_it].reshape(t * k, m))
        thr = jnp.asarray(self.threshold_value[:n_it].reshape(t * k, m))
        cm = None
        if self.cat_mask is not None:
            cm = jnp.asarray(self.cat_mask[:n_it].reshape(t * k, m, -1))
        return np.asarray(T.leaf_index_forest(x, feat, thr, self.max_depth,
                                              cat_masks=cm))

    # ---------------- introspection ----------------
    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        """Per-feature importance: 'split' counts or total 'gain'
        (reference ``LightGBMBooster.getFeatureImportances``)."""
        flat_feat = self.feature.reshape(-1)
        out = np.zeros(self.num_features, dtype=np.float64)
        if importance_type == "split":
            valid = flat_feat >= 0
            np.add.at(out, flat_feat[valid], 1.0)
        elif importance_type == "gain":
            flat_gain = self.gain.reshape(-1)
            valid = flat_feat >= 0
            np.add.at(out, flat_feat[valid], flat_gain[valid])
        else:
            raise ValueError(f"importance_type must be 'split' or 'gain', got {importance_type}")
        return out

    # ---------------- persistence ----------------
    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        arrays = dict(feature=self.feature, threshold_value=self.threshold_value,
                      leaf_value=self.leaf_value, gain=self.gain,
                      init_score=self.init_score)
        if self.cover is not None:
            arrays["cover"] = self.cover
        if self.cat_mask is not None:
            arrays["cat_mask"] = self.cat_mask
        np.savez_compressed(os.path.join(path, "trees.npz"), **arrays)
        meta = {
            "max_depth": self.max_depth, "num_model_out": self.num_model_out,
            "objective": self.objective, "num_features": self.num_features,
            "params": self.params, "best_iteration": self.best_iteration,
            "average_output": self.average_output,
            "categorical_features": list(self.categorical_features),
        }
        with open(os.path.join(path, "booster.json"), "w") as f:
            json.dump(meta, f, indent=2)

    @classmethod
    def load(cls, path: str) -> "TpuBooster":
        with open(os.path.join(path, "booster.json")) as f:
            meta = json.load(f)
        z = np.load(os.path.join(path, "trees.npz"))
        return cls(z["feature"], z["threshold_value"], z["leaf_value"], z["gain"],
                   init_score=z["init_score"],
                   cover=z["cover"] if "cover" in z.files else None,
                   average_output=meta.get("average_output", False),
                   cat_mask=z["cat_mask"] if "cat_mask" in z.files else None,
                   categorical_features=tuple(
                       meta.get("categorical_features", ())),
                   **{k: meta[k] for k in
                   ("max_depth", "num_model_out", "objective", "num_features",
                    "params", "best_iteration")})

    def dump_text(self) -> str:
        """Human-readable model dump (the reference's saveNativeModel string
        role — our own format, not LightGBM's)."""
        lines = [f"tpu_booster objective={self.objective} trees={self.num_iterations}"
                 f"x{self.num_model_out} max_depth={self.max_depth} "
                 f"num_features={self.num_features}"]
        for t in range(self.num_iterations):
            for k in range(self.num_model_out):
                lines.append(f"tree {t}.{k}:")
                for i in range(self.feature.shape[2]):
                    f_ = int(self.feature[t, k, i])
                    if f_ >= 0 and f_ in self.categorical_features \
                            and self.cat_mask is not None \
                            and self.cat_mask[t, k, i].any():
                        cats = np.nonzero(self.cat_mask[t, k, i])[0].tolist()
                        lines.append(f"  node {i}: f{f_} in {cats} "
                                     f"-> {2*i+1},{2*i+2}")
                    elif f_ >= 0:
                        lines.append(f"  node {i}: f{f_} <= "
                                     f"{float(self.threshold_value[t, k, i]):.6g} "
                                     f"-> {2*i+1},{2*i+2}")
                    elif self.leaf_value[t, k, i] != 0.0:
                        lines.append(f"  leaf {i}: {float(self.leaf_value[t, k, i]):.6g}")
        return "\n".join(lines)


def fold_positive_class_weight(y: np.ndarray, w: np.ndarray, *,
                               objective: str, is_unbalance: bool,
                               scale_pos_weight: float) -> np.ndarray:
    """Positive-class reweighting (reference scalePosWeight/isUnbalance),
    folded into the sample-weight vector. The ONE copy of this formula:
    serial ``train_booster`` and the fused sweep's ``_fit_fused`` both call
    it, so fused-vs-serial parity on unbalanced data cannot drift."""
    if is_unbalance and scale_pos_weight != 1.0:
        # match LightGBM: the two knobs conflict
        raise ValueError("set either is_unbalance or scale_pos_weight, not both")
    if objective != "binary" or not (is_unbalance or scale_pos_weight != 1.0):
        return w
    pos = y > 0
    spw = scale_pos_weight
    if is_unbalance:
        n_pos = max(int(pos.sum()), 1)
        spw = (len(y) - n_pos) / n_pos
    return np.where(pos, w * spw, w)


def _checked_monotone(constraints, num_features: int) -> tuple:
    """Validate per-feature monotone constraints (silent broadcast/clamp under
    jit would misapply a wrong-length list)."""
    if constraints is None:
        return ()
    out = tuple(int(c) for c in constraints)
    if len(out) != num_features:
        raise ValueError(f"monotone_constraints has {len(out)} entries for "
                         f"{num_features} features")
    if any(c not in (-1, 0, 1) for c in out):
        raise ValueError(f"monotone_constraints entries must be -1/0/+1: {out}")
    return out if any(out) else ()  # all-zero == unconstrained


def _device_put_sharded(arr: jax.Array, mesh) -> jax.Array:
    if mesh is None:
        return jnp.asarray(arr)
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P("data", *([None] * (arr.ndim - 1)))
    return jax.device_put(jnp.asarray(arr), NamedSharding(mesh, spec))


def train_booster(features: np.ndarray, labels: np.ndarray, *,
                  objective: str = "regression", num_class: int = 1,
                  num_iterations: int = 100, learning_rate: float = 0.1,
                  num_leaves: int = 31, max_depth: int = -1, max_bin: int = 255,
                  lambda_l1: float = 0.0, lambda_l2: float = 0.0,
                  min_data_in_leaf: int = 20, min_sum_hessian: float = 1e-3,
                  min_gain_to_split: float = 0.0, feature_fraction: float = 1.0,
                  bagging_fraction: float = 1.0, bagging_freq: int = 0,
                  weights: np.ndarray | None = None,
                  group_sizes: np.ndarray | None = None,
                  valid_features: np.ndarray | None = None,
                  valid_labels: np.ndarray | None = None,
                  valid_group_sizes: np.ndarray | None = None,
                  early_stopping_round: int = 0, seed: int = 0,
                  mesh=None, objective_alpha: float | None = None,
                  tweedie_variance_power: float | None = None,
                  callbacks: Sequence[Callable] | None = None,
                  boosting_type: str = "gbdt", top_rate: float = 0.2,
                  other_rate: float = 0.1, drop_rate: float = 0.1,
                  max_drop: int = 50, skip_drop: float = 0.5,
                  monotone_constraints=None, scale_pos_weight: float = 1.0,
                  is_unbalance: bool = False, histogram_impl: str = "segment",
                  categorical_features=None, init_model=None,
                  measures=None, verbose: bool = False) -> TpuBooster:
    """Grow a forest. The full binned matrix + running scores stay on device
    for the whole run; pass ``mesh`` to shard rows over its ``data`` axis
    (multi-host DP — the reference's NetworkManager/ring role).

    ``boosting_type``: 'gbdt' | 'goss' (gradient one-side sampling, LightGBM
    top_rate/other_rate semantics) | 'dart' (tree dropout with 1/(k+1)
    normalization) | 'rf' (bagged trees on init-score gradients, averaged
    output) — the reference's boostingType surface
    (``params/LightGBMParams.scala``)."""
    if boosting_type not in ("gbdt", "goss", "dart", "rf"):
        raise ValueError(f"boosting_type must be gbdt|goss|dart|rf, got "
                         f"{boosting_type!r}")
    if measures is None:
        from ..core.instrumentation import InstrumentationMeasures

        measures = InstrumentationMeasures()
    from ..core import observability as _obs

    # per-iteration step times feed the unified metrics plane so the bench
    # trajectory carries a p50/p95/p99 distribution, not just the summed
    # `training_ms` window
    step_hist = _obs.get_registry().histogram(
        "synapseml_train_step_duration_ms",
        "training step (boosting iteration / optimizer step) wall time",
        ("engine",)).labels(engine="gbdt")
    # keep the caller's dtype: float32 input takes the multithreaded native
    # binning path (BinMapper.transform); boundary FITTING widens to float64
    # inside BinMapper either way, so bin codes are dtype-independent
    x = np.asarray(features)
    y = np.asarray(labels, dtype=np.float32)
    n, f = x.shape
    max_depth = T.derive_max_depth(max_depth, num_leaves)

    cat_feats = tuple(sorted(int(i) for i in (categorical_features or ())))
    if cat_feats and not all(0 <= i < f for i in cat_feats):
        raise ValueError(f"categorical_features out of range [0, {f}): {cat_feats}")
    mapper = BinMapper(max_bin=max_bin, seed=seed, categorical=cat_feats)
    with measures.measure("binning"):  # the reference's dataset-prep window
        bins_np = mapper.fit_transform(x).astype(np.int32)

    # pad rows to a multiple of the data-axis size for even sharding
    pad = 0
    if mesh is not None:
        dsize = mesh.shape.get("data", 1)
        pad = (-n) % dsize
    if pad:
        bins_np = np.concatenate([bins_np, np.zeros((pad, f), np.int32)])
        y = np.concatenate([y, np.zeros(pad, np.float32)])
    presence_np = np.ones(n + pad, np.float32)
    if pad:
        presence_np[n:] = 0.0
    w_np = np.ones(n + pad, np.float32)
    if weights is not None:
        w_np[:n] = np.asarray(weights, dtype=np.float32)
    w_np[:n] = fold_positive_class_weight(
        y[:n], w_np[:n], objective=objective, is_unbalance=is_unbalance,
        scale_pos_weight=scale_pos_weight)

    obj_kw = {}
    if objective_alpha is not None:
        obj_kw["alpha"] = objective_alpha
    if tweedie_variance_power is not None:
        obj_kw["tweedie_variance_power"] = tweedie_variance_power
    o = obj.get_objective(objective, num_class=num_class, **obj_kw)
    if o.name in ("poisson", "tweedie", "gamma") and np.any(y[:n] < 0):
        # stock LightGBM fails fast too: negative labels flip the hessian
        # sign under the log link and silently destabilize leaf weights
        raise ValueError(f"{o.name} objective requires non-negative labels")
    K = o.num_model_out

    with measures.measure("device_transfer"):
        bins = _device_put_sharded(bins_np, mesh)
        yd = _device_put_sharded(y, mesh)
        base_presence = _device_put_sharded(presence_np, mesh)
        wd = _device_put_sharded(w_np, mesh)

    # ranking: bind padded-group lambda computation
    is_rank = o.name == "lambdarank"
    if is_rank:
        if group_sizes is None:
            raise ValueError("lambdarank requires group_sizes")
        gslot, gmax = obj.pad_groups(group_sizes)
        if pad:
            extra = np.stack([np.arange(pad) * 0 + len(group_sizes),
                              np.arange(pad)], axis=1).astype(np.int32)
            # padded rows go to a throwaway group
            gslot = np.concatenate([gslot, extra])
            ngroups = len(group_sizes) + 1
            gmax = max(gmax, pad)
        else:
            ngroups = len(group_sizes)
        gslot_d = jnp.asarray(gslot)

        @jax.jit
        def grad_hess(scores, yv):
            g, h = obj.lambdarank_grad_hess(scores[:, 0], yv, gslot_d, ngroups, gmax)
            return g[:, None], h[:, None]

        @jax.jit
        def metric(scores, yv):
            return -obj.ndcg_at_k(scores[:, 0], yv, gslot_d, ngroups, gmax)
        init = np.zeros(1, np.float32)
    else:
        @jax.jit
        def grad_hess(scores, yv):
            g, h = o.grad_hess(scores, yv)
            return g.reshape(scores.shape[0], -1), h.reshape(scores.shape[0], -1)

        metric = jax.jit(o.metric)
        init = np.asarray(jax.device_get(o.init_score(jnp.asarray(y[:n]))), np.float32).reshape(K)

    # warm start (reference modelString continuation, LightGBMBase.scala:48-60):
    # training resumes from the previous booster's raw margins; its trees are
    # prepended to the returned model
    prev = None
    if init_model is not None:
        if isinstance(init_model, (str, bytes)):
            from .interop import parse_lightgbm_string

            prev = parse_lightgbm_string(init_model if isinstance(init_model, str)
                                         else init_model.decode())
        else:
            prev = init_model
        if prev.num_features != f:
            raise ValueError(f"init_model has {prev.num_features} features, "
                             f"data has {f}")
        if prev.num_model_out != K:
            raise ValueError(f"init_model outputs {prev.num_model_out} models "
                             f"per iteration, this objective needs {K}")
        if prev.average_output:
            raise ValueError("continued training from an rf (averaged) model "
                             "is not supported (matches LightGBM)")
        if boosting_type == "rf":
            raise ValueError("boosting_type='rf' cannot continue from "
                             "init_model: averaged output would fold the "
                             "previous full-weight trees into the mean")
        init = np.asarray(prev.init_score, np.float32).reshape(K)
        base = np.asarray(prev.raw_score(x), np.float32).reshape(n, K)
        scores_np = np.broadcast_to(init[None, :], (n + pad, K)).copy()
        scores_np[:n] = base
        scores = _device_put_sharded(scores_np.astype(np.float32), mesh)
    else:
        scores = jnp.broadcast_to(jnp.asarray(init)[None, :], (n + pad, K)).astype(jnp.float32)
        scores = _device_put_sharded(np.asarray(scores), mesh)

    cfg = T.GrowthConfig(max_depth=max_depth, num_leaves=num_leaves,
                         num_bins=mapper.num_bins, lambda_l1=lambda_l1,
                         lambda_l2=lambda_l2,
                         monotone_constraints=_checked_monotone(monotone_constraints, f),
                         # rf: no shrinkage, output is averaged (LightGBM forces
                         # shrinkage 1 in rf mode)
                         learning_rate=1.0 if boosting_type == "rf" else learning_rate,
                         min_data_in_leaf=min_data_in_leaf,
                         min_sum_hessian=min_sum_hessian,
                         min_gain_to_split=min_gain_to_split,
                         hist_impl=histogram_impl,
                         categorical_features=cat_feats)

    # validation state (kept binned; scores updated incrementally)
    has_valid = valid_features is not None and valid_labels is not None
    if has_valid:
        vbins = jnp.asarray(mapper.transform(np.asarray(valid_features)).astype(np.int32))
        vy = jnp.asarray(np.asarray(valid_labels, np.float32))
        if prev is not None:  # warm start: eval continues from prev margins too
            vscores = jnp.asarray(np.asarray(prev.raw_score(
                np.asarray(valid_features, np.float32)), np.float32))
        else:
            vscores = jnp.broadcast_to(jnp.asarray(init)[None, :],
                                       (vbins.shape[0], K)).astype(jnp.float32)
        if is_rank:
            if valid_group_sizes is None:
                raise ValueError("lambdarank validation requires valid_group_sizes")
            vslot, vmax = obj.pad_groups(valid_group_sizes)
            vslot_d = jnp.asarray(vslot)
            vngroups = len(valid_group_sizes)

            @jax.jit
            def vmetric(s, yv):
                return -obj.ndcg_at_k(s[:, 0], yv, vslot_d, vngroups, vmax)
        else:
            vmetric = metric

    # ---- fused iteration step: grad/hess + K trees + score updates in ONE
    # dispatch (the reference's LGBM_BoosterUpdateOneIter hot-loop role; the
    # round-1 host loop dispatched ~(depth+3)*K programs per iteration and
    # synced every tree's arrays to host). RNG (bagging/feature sampling)
    # lives on-device too, so the whole run can optionally lax.scan.
    key0 = jax.random.PRNGKey(seed)
    # Disjoint key domains per sampling purpose: folding purpose first, then
    # iteration, can never collide across purposes (the old 2*it/3*it+2
    # counter scheme reused identical derived keys, e.g. GOSS it=1 ==
    # feature-fraction it=2).
    key_bag = jax.random.fold_in(key0, 0)
    key_feat = jax.random.fold_in(key0, 1)
    key_goss = jax.random.fold_in(key0, 2)
    k_feat = max(1, int(round(f * feature_fraction)))
    if boosting_type == "rf" and not (bagging_fraction < 1.0 and bagging_freq > 0):
        # rf requires bagging (LightGBM errors; we default it on)
        bagging_fraction, bagging_freq = 0.632, 1
    do_bag = (bagging_fraction < 1.0 and bagging_freq > 0
              and boosting_type != "goss")  # goss replaces bagging
    k_top = max(1, int(round(top_rate * n)))

    def _masks(it):
        if do_bag:
            # LightGBM semantics: resample every bagging_freq iters, keep the
            # bag in between
            bkey = jax.random.fold_in(key_bag, it - it % bagging_freq)
            bag = (jax.random.uniform(bkey, (n + pad,)) <
                   bagging_fraction).astype(jnp.float32)
        else:
            bag = jnp.ones(n + pad, jnp.float32)
        if feature_fraction < 1.0:
            fkey = jax.random.fold_in(key_feat, it)
            ranks = jnp.argsort(jnp.argsort(jax.random.uniform(fkey, (f,))))
            fmask = ranks < k_feat
        else:
            fmask = jnp.ones(f, bool)
        return bag, fmask

    def make_iteration(update_train: bool = True, update_valid: bool = True):
        def one_iteration(data, carry, it):
            # explicit data args, NOT closures: multi-process sharded arrays
            # may not be closed over by jitted functions
            bins, yd, base_presence, wd, vbins = data
            scores, vscores = carry
            bag, fmask = _masks(it)
            presence = base_presence * bag
            g, h = grad_hess(scores, yd)
            if boosting_type == "goss":
                # keep the top_rate fraction by |grad|, sample other_rate of
                # the rest, amplify the sampled small-gradient rows
                gmag = jnp.sum(jnp.abs(g), axis=1) * wd * base_presence
                thresh = jnp.sort(gmag)[-k_top]
                is_top = gmag >= thresh
                rkey = jax.random.fold_in(key_goss, it)
                sampled = (~is_top) & (jax.random.uniform(rkey, (n + pad,))
                                       < other_rate)
                sel = (is_top | sampled).astype(jnp.float32)
                amp = (1.0 - top_rate) / max(other_rate, 1e-12)
                w_goss = jnp.where(is_top, 1.0, amp) * sel
                presence = base_presence * sel
                w_eff = (wd * w_goss * base_presence)[:, None]
            else:
                w_eff = (wd * presence)[:, None]  # pads/bagged-out: zero grad AND count
            g = g * w_eff
            h = h * w_eff

            def per_class(sc_pair, gh_k):
                scores, vscores = sc_pair
                gk, hk, k_idx = gh_k
                tree = T.grow_tree(bins, gk, hk, presence, cfg, fmask)
                if update_train:
                    delta = T.traverse_binned(bins, tree, max_depth)
                    scores = jax.lax.dynamic_update_index_in_dim(
                        scores, scores[:, k_idx] + delta, k_idx, axis=1)
                if has_valid and update_valid:
                    vd = T.traverse_binned(vbins, tree, max_depth)
                    vscores = jax.lax.dynamic_update_index_in_dim(
                        vscores, vscores[:, k_idx] + vd, k_idx, axis=1)
                return (scores, vscores), tree

            (scores, vscores), trees = jax.lax.scan(
                per_class, (scores, vscores),
                (jnp.swapaxes(g, 0, 1), jnp.swapaxes(h, 0, 1),
                 jnp.arange(K, dtype=jnp.int32)))
            return (scores, vscores), trees
        return one_iteration

    one_iteration = make_iteration(update_train=boosting_type != "rf")
    # the validation bins ride in the bundle only when they exist
    data = (bins, yd, base_presence, wd, vbins if has_valid else bins[:1])

    if not has_valid:
        vscores = jnp.zeros((1, K), jnp.float32)  # placeholder carry leaf

    best_metric, best_iter, since_best = np.inf, None, 0
    use_full_scan = (not (has_valid and early_stopping_round > 0)
                     and not callbacks and boosting_type != "dart")

    def check_early_stop(it, vscores, on_best=None) -> bool:
        """Shared early-stopping bookkeeping; returns True to stop."""
        nonlocal best_metric, best_iter, since_best
        if not (has_valid and early_stopping_round > 0):
            return False
        v_eval = vscores
        if boosting_type == "rf":
            # rf predicts the AVERAGE of trees: metric on init + mean
            v_eval = jnp.asarray(init)[None, :] + \
                (vscores - jnp.asarray(init)[None, :]) / (it + 1)
        m = float(vmetric(v_eval, vy))
        if verbose:
            print(f"[{it}] valid {o.metric_name}={m:.6f}")
        if m < best_metric - 1e-12:
            best_metric, best_iter, since_best = m, it + 1, 0
            if on_best is not None:
                on_best()
        else:
            since_best += 1
            if since_best >= early_stopping_round:
                return True
        return False

    def forest_delta(feat_s, thr_s, val_s, cm_s, data_bins):
        """Summed per-class outputs of a stack of trees: (D, K, M) -> (N, K)."""
        def one(acc, tkm):
            fe, th, va, cm = tkm

            def per_k(c, fkv):
                f1, t1, v1, c1 = fkv
                tree = T.TreeArrays(f1, t1, v1, v1, v1, c1)  # gain/cover unused
                return c, T.traverse_binned(data_bins, tree, max_depth)

            _, deltas = jax.lax.scan(per_k, 0, (fe, th, va, cm))  # (K, N)
            return acc + jnp.swapaxes(deltas, 0, 1), None

        out0 = jnp.zeros((data_bins.shape[0], K), jnp.float32)
        out, _ = jax.lax.scan(one, out0, (feat_s, thr_s, val_s, cm_s))
        return out

    if use_full_scan:
        # no per-iteration host decision needed: the ENTIRE training run is
        # one compiled program
        @jax.jit
        def run_all(data, scores, vscores):
            return jax.lax.scan(lambda c, i: one_iteration(data, c, i),
                                (scores, vscores),
                                jnp.arange(num_iterations, dtype=jnp.int32))

        t_scan = time.perf_counter()
        with measures.measure("training"):
            (scores, vscores), trees = run_all(data, scores, vscores)
            jax.block_until_ready(trees.feature)
        measures.count("iterations", num_iterations)
        # the whole run is one dispatch: record the amortized per-step time
        step_hist.observe((time.perf_counter() - t_scan) * 1e3
                          / max(num_iterations, 1))
        feat_dev, thr_dev = trees.feature, trees.threshold_bin   # (T, K, M)
        val_dev, gain_dev, cover_dev = trees.leaf_value, trees.gain, trees.cover
        cat_dev = trees.cat_mask
    elif boosting_type == "dart":
        # DART (tree dropout): per iteration, drop a random subset of grown
        # trees, fit against the reduced scores, then renormalize — new tree
        # by 1/(k+1), dropped trees by k/(k+1). Inherently sequential (past
        # trees mutate), so this always runs the host loop.
        forest_delta_j = jax.jit(forest_delta)
        dart_iter = jax.jit(make_iteration(update_train=False, update_valid=False))
        drop_rng = np.random.default_rng(seed + 17)
        acc_f, acc_t, acc_v, acc_g, acc_c, acc_cm = [], [], [], [], [], []
        # later drops rescale EARLIER trees' leaf values in place, so the
        # model measured at best_iter is only reproducible from a snapshot
        best_v = None

        def snapshot():
            nonlocal best_v
            best_v = list(acc_v)

        for it in range(num_iterations):
            t_iter = time.perf_counter()
            dropped: list[int] = []
            if acc_f and drop_rng.random() >= skip_drop:
                mask = drop_rng.random(len(acc_f)) < drop_rate
                dropped = [int(i) for i in np.nonzero(mask)[0][:max_drop]]
                if not dropped:
                    dropped = [int(drop_rng.integers(len(acc_f)))]
            measures.count("iterations")
            vdelta_drop = None
            if dropped:
                measures.count("trees_dropped", len(dropped))
            if dropped:
                fs = jnp.stack([acc_f[i] for i in dropped])
                ts = jnp.stack([acc_t[i] for i in dropped])
                vs = jnp.stack([acc_v[i] for i in dropped])
                cs = jnp.stack([acc_cm[i] for i in dropped])
                delta_drop = forest_delta_j(fs, ts, vs, cs, bins)
                scores_red = scores - delta_drop
                if has_valid:
                    vdelta_drop = forest_delta_j(fs, ts, vs, cs, vbins)
                    vscores = vscores - vdelta_drop
            else:
                scores_red = scores
            _, trees = dart_iter(data, (scores_red, vscores),
                                 jnp.asarray(it, jnp.int32))
            kd = len(dropped)
            norm_new = 1.0 / (kd + 1)
            delta_new = forest_delta_j(trees.feature[None], trees.threshold_bin[None],
                                       trees.leaf_value[None],
                                       trees.cat_mask[None], bins)
            scores = scores_red + delta_new * norm_new
            if has_valid:
                vdelta_new = forest_delta_j(trees.feature[None],
                                            trees.threshold_bin[None],
                                            trees.leaf_value[None],
                                            trees.cat_mask[None], vbins)
                vscores = vscores + vdelta_new * norm_new
            if dropped:
                norm_drop = kd / (kd + 1.0)
                for i in dropped:
                    acc_v[i] = acc_v[i] * norm_drop
                scores = scores + delta_drop * norm_drop
                if has_valid:
                    vscores = vscores + vdelta_drop * norm_drop
            acc_f.append(trees.feature)
            acc_t.append(trees.threshold_bin)
            acc_v.append(trees.leaf_value * norm_new)
            acc_g.append(trees.gain)
            acc_c.append(trees.cover)
            acc_cm.append(trees.cat_mask)
            step_hist.observe((time.perf_counter() - t_iter) * 1e3)
            if callbacks:
                for cb in callbacks:
                    cb(iteration=it, scores=scores)
            if check_early_stop(it, vscores, on_best=snapshot):
                break
        if best_iter is not None and best_v is not None:
            # return exactly the model that was measured best: its trees with
            # their scales AS OF that iteration
            acc_f, acc_t = acc_f[:best_iter], acc_t[:best_iter]
            acc_g, acc_c = acc_g[:best_iter], acc_c[:best_iter]
            acc_cm = acc_cm[:best_iter]
            acc_v = best_v[:best_iter]
        feat_dev = jnp.stack(acc_f)
        thr_dev = jnp.stack(acc_t)
        val_dev = jnp.stack(acc_v)
        gain_dev = jnp.stack(acc_g)
        cover_dev = jnp.stack(acc_c)
        cat_dev = jnp.stack(acc_cm)
    else:
        iter_jit = jax.jit(one_iteration)
        acc_f, acc_t, acc_v, acc_g, acc_c, acc_cm = [], [], [], [], [], []
        for it in range(num_iterations):
            measures.count("iterations")
            t_iter = time.perf_counter()
            with measures.measure("training"):
                (scores, vscores), trees = iter_jit(
                    data, (scores, vscores), jnp.asarray(it, jnp.int32))
            step_hist.observe((time.perf_counter() - t_iter) * 1e3)
            # device arrays accumulate WITHOUT host sync; fetched once at the end
            acc_f.append(trees.feature)
            acc_t.append(trees.threshold_bin)
            acc_v.append(trees.leaf_value)
            acc_g.append(trees.gain)
            acc_c.append(trees.cover)
            acc_cm.append(trees.cat_mask)
            if callbacks:
                for cb in callbacks:
                    cb(iteration=it, scores=scores)
            if check_early_stop(it, vscores):
                break
        with measures.measure("training"):
            jax.block_until_ready(acc_f[-1])  # fold trailing async into the window
        feat_dev = jnp.stack(acc_f)
        thr_dev = jnp.stack(acc_t)
        val_dev = jnp.stack(acc_v)
        gain_dev = jnp.stack(acc_g)
        cover_dev = jnp.stack(acc_c)
        cat_dev = jnp.stack(acc_cm)

    # ONE host transfer for the whole forest; bin->value thresholds on host
    measures.mark("train_done")
    ub = mapper.upper_bound_values()
    feat_h = np.asarray(feat_dev)
    thr_bin_h = np.asarray(thr_dev)
    thr_val_h = np.where(feat_h >= 0,
                         ub[np.maximum(feat_h, 0), thr_bin_h], 0.0).astype(np.float32)
    cat_mask_h = None
    if cat_feats:
        cat_mask_h = np.asarray(cat_dev, np.uint8)  # (T, K, M, B)
        is_cat_lut = np.zeros(f + 1, bool)
        is_cat_lut[list(cat_feats)] = True
        # categorical nodes carry the left SET, not a threshold value
        thr_val_h = np.where(is_cat_lut[np.maximum(feat_h, 0)] & (feat_h >= 0),
                             0.0, thr_val_h).astype(np.float32)

    val_h, gain_h, cover_h = (np.asarray(val_dev), np.asarray(gain_dev),
                              np.asarray(cover_dev))
    if prev is not None and not hasattr(prev, "feature"):
        # imported model.txt continuation: imported trees use child-array
        # layout (depth unbounded — not heap-expressible), so the merge
        # happens in LightGBM format: new trees export to model.txt and the
        # concatenated forest reparses into one ImportedBooster (scoring-
        # surface compatible with the model transformers)
        from .interop import parse_lightgbm_string, to_lightgbm_string

        new_b = TpuBooster(
            feat_h, thr_val_h, val_h, gain_h, cover=cover_h,
            max_depth=max_depth, num_model_out=K, objective=o.name,
            init_score=np.zeros(K, np.float32),  # increments on prev margins
            num_features=f, best_iteration=best_iter,
            cat_mask=cat_mask_h, categorical_features=cat_feats)
        new_imported = parse_lightgbm_string(to_lightgbm_string(new_b))
        import dataclasses as _dc

        # resume was from best_iteration-truncated margins: stale post-best
        # trees must not ride into the merged forest
        n_prev = (prev.best_iteration or prev.num_iterations) * prev.num_model_out
        merged = _dc.replace(prev, trees=list(prev.trees[:n_prev])
                             + list(new_imported.trees),
                             best_iteration=None)
        merged.bin_mapper = mapper
        merged.train_measures = measures.to_dict()
        return merged
    if prev is not None:
        # prepend the previous forest; a shallower heap layout embeds into a
        # deeper one unchanged (node ids are depth-invariant), so pad node
        # arrays to the larger M with leaf defaults
        depth_all = max(max_depth, prev.max_depth)
        M = 2 ** (depth_all + 1) - 1

        def pad_nodes(a, fill=0.0):
            return np.pad(a, ((0, 0), (0, 0), (0, M - a.shape[2])),
                          constant_values=fill)

        if (prev.cat_mask is None) != (cat_mask_h is None) or (
                prev.cat_mask is not None
                and prev.cat_mask.shape[-1] != cat_mask_h.shape[-1]):
            raise ValueError(
                "continued training with categorical features requires "
                "the same max_bin/categorical setup as init_model")
        # resume was from best_iteration-truncated margins: slice stale
        # post-best trees away before prepending
        n_prev = prev.best_iteration or prev.num_iterations
        feat_h = np.concatenate([pad_nodes(prev.feature[:n_prev], -1),
                                 pad_nodes(feat_h, -1)])
        thr_val_h = np.concatenate([pad_nodes(prev.threshold_value[:n_prev]),
                                    pad_nodes(thr_val_h)])
        val_h = np.concatenate([pad_nodes(prev.leaf_value[:n_prev]),
                                pad_nodes(val_h)])
        gain_h = np.concatenate([pad_nodes(prev.gain[:n_prev]),
                                 pad_nodes(gain_h)])
        prev_cover = (prev.cover if prev.cover is not None
                      else np.zeros_like(prev.gain))
        cover_h = np.concatenate([pad_nodes(prev_cover[:n_prev]),
                                  pad_nodes(cover_h)])
        if cat_mask_h is not None:
            cm_pad = lambda a: np.pad(  # noqa: E731
                a, ((0, 0), (0, 0), (0, M - a.shape[2]), (0, 0)))
            cat_mask_h = np.concatenate([cm_pad(prev.cat_mask[:n_prev]),
                                         cm_pad(cat_mask_h)])
        max_depth = depth_all
        best_iter = (n_prev + best_iter) if best_iter else None

    booster = TpuBooster(
        feat_h, thr_val_h, val_h, gain_h,
        cover=cover_h,
        max_depth=max_depth, num_model_out=K, objective=o.name, init_score=init,
        num_features=f, best_iteration=best_iter,
        average_output=boosting_type == "rf",
        cat_mask=cat_mask_h, categorical_features=cat_feats,
        params={"num_iterations": num_iterations, "learning_rate": learning_rate,
                "num_leaves": num_leaves, "max_bin": max_bin,
                "boosting_type": boosting_type})
    booster.bin_mapper = mapper
    booster.train_measures = measures.to_dict()
    return booster
