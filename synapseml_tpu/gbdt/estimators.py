"""LightGBMClassifier / LightGBMRegressor / LightGBMRanker estimators.

Reference: ``lightgbm/.../LightGBMClassifier.scala:212`` area,
``LightGBMRegressor.scala``, ``LightGBMRanker.scala`` and the shared param
surface of ``params/LightGBMParams.scala`` (~100 params flattened into a
native param string). Here the estimator params map 1:1 onto
:func:`synapseml_tpu.gbdt.booster.train_booster` keywords; the native engine
is the XLA histogram forest of :mod:`synapseml_tpu.gbdt.trees`.

Training data flows the streaming-mode way (``StreamingPartitionTask.scala``):
partitions are concatenated host-side into one binned matrix that is placed
(optionally sharded over the mesh ``data`` axis) into HBM once.
"""

from __future__ import annotations

import numpy as np

from ..core import DataFrame, Estimator, Model
from ..core.params import ComplexParam, Param, TypeConverters

__all__ = [
    "LightGBMClassifier", "LightGBMClassificationModel",
    "LightGBMRegressor", "LightGBMRegressionModel",
    "LightGBMRanker", "LightGBMRankerModel",
]


class _LightGBMParams:
    """Shared train params (reference ``params/LightGBMParams.scala``)."""

    features_col = Param("features_col", "features column: one (N,F) array column, "
                         "or set feature_cols for separate numeric columns",
                         default="features")
    feature_cols = Param("feature_cols", "explicit list of numeric feature columns "
                         "(alternative to an assembled features_col)", default=None)
    label_col = Param("label_col", "label column", default="label")
    weight_col = Param("weight_col", "sample weight column", default=None)
    prediction_col = Param("prediction_col", "prediction output column", default="prediction")
    validation_indicator_col = Param(
        "validation_indicator_col", "boolean column marking validation rows "
        "(reference validationIndicatorCol)", default=None)

    num_iterations = Param("num_iterations", "boosting rounds", default=100,
                           converter=TypeConverters.to_int)
    learning_rate = Param("learning_rate", "shrinkage", default=0.1,
                          converter=TypeConverters.to_float)
    num_leaves = Param("num_leaves", "max leaves per tree", default=31,
                       converter=TypeConverters.to_int)
    max_depth = Param("max_depth", "max depth (-1 = derive from num_leaves)",
                      default=-1, converter=TypeConverters.to_int)
    max_bin = Param("max_bin", "histogram bins per feature", default=255,
                    converter=TypeConverters.to_int)
    lambda_l1 = Param("lambda_l1", "L1 regularization", default=0.0,
                      converter=TypeConverters.to_float)
    lambda_l2 = Param("lambda_l2", "L2 regularization", default=0.0,
                      converter=TypeConverters.to_float)
    min_data_in_leaf = Param("min_data_in_leaf", "min rows per leaf", default=20,
                             converter=TypeConverters.to_int)
    min_sum_hessian_in_leaf = Param("min_sum_hessian_in_leaf", "min hessian per leaf",
                                    default=1e-3, converter=TypeConverters.to_float)
    min_gain_to_split = Param("min_gain_to_split", "min split gain", default=0.0,
                              converter=TypeConverters.to_float)
    feature_fraction = Param("feature_fraction", "per-tree feature subsample",
                             default=1.0, converter=TypeConverters.to_float)
    bagging_fraction = Param("bagging_fraction", "row subsample fraction", default=1.0,
                             converter=TypeConverters.to_float)
    bagging_freq = Param("bagging_freq", "bagging every k iterations (0=off)",
                         default=0, converter=TypeConverters.to_int)
    boosting_type = Param("boosting_type", "gbdt | goss | dart | rf "
                          "(reference boostingType)", default="gbdt")
    top_rate = Param("top_rate", "goss: keep fraction by |grad|", default=0.2,
                     converter=TypeConverters.to_float)
    other_rate = Param("other_rate", "goss: sample fraction of the rest",
                       default=0.1, converter=TypeConverters.to_float)
    drop_rate = Param("drop_rate", "dart: per-tree dropout probability",
                      default=0.1, converter=TypeConverters.to_float)
    max_drop = Param("max_drop", "dart: max trees dropped per iteration",
                     default=50, converter=TypeConverters.to_int)
    skip_drop = Param("skip_drop", "dart: probability of skipping dropout",
                      default=0.5, converter=TypeConverters.to_float)
    monotone_constraints = ComplexParam(
        "monotone_constraints", "per-feature +1/-1/0 monotonicity "
        "(reference monotoneConstraints; 'basic' method)", default=None)
    categorical_slot_indexes = ComplexParam(
        "categorical_slot_indexes", "feature indices treated as categorical "
        "codes: LightGBM many-vs-many splits on sorted-gradient prefixes "
        "(reference categoricalSlotIndexes, params/LightGBMParams.scala)",
        default=None)
    early_stopping_round = Param("early_stopping_round", "stop after k rounds without "
                                 "validation improvement (0=off)", default=0,
                                 converter=TypeConverters.to_int)
    seed = Param("seed", "random seed", default=0, converter=TypeConverters.to_int)
    histogram_impl = Param("histogram_impl", "histogram backend: segment "
                           "(scatter-add) | onehot (XLA matmul) | pallas "
                           "(fused VMEM one-hot kernel); equivalent results, "
                           "pick by measurement "
                           "(benchmarks/gbdt_hist_backends.py)",
                           default="segment",
                           validator=lambda v: v in ("segment", "onehot",
                                                     "pallas"))
    verbosity = Param("verbosity", "print eval metrics when > 0", default=-1,
                      converter=TypeConverters.to_int)
    model_string = ComplexParam(
        "model_string", "previous booster (TpuBooster or LightGBM model.txt "
        "string) to continue training from (reference modelString, "
        "LightGBMBase.scala:48-60)", default=None)
    mesh_config = ComplexParam("mesh_config", "MeshConfig to shard rows over the "
                               "mesh data axis (multi-host training)", default=None)

    # estimator param name -> fused_train_boosters trial key: the scalar,
    # architecture-preserving knobs that ride a horizontally fused training
    # array as traced per-trial inputs (one executable for any values)
    _FUSED_SCALAR_PARAMS = {
        "learning_rate": "learning_rate", "lambda_l1": "lambda_l1",
        "lambda_l2": "lambda_l2", "num_leaves": "num_leaves",
        "min_data_in_leaf": "min_data_in_leaf",
        "min_sum_hessian_in_leaf": "min_sum_hessian",
        "min_gain_to_split": "min_gain_to_split",
        "num_iterations": "num_iterations",
    }

    def _fused_plan(self, cfg: dict):
        """Fusability contract for ``automl.tune``: a hashable signature when
        ``self.copy(cfg).fit(df)`` can train inside a fused GBDT array, else
        ``None`` (serial path). Candidates with EQUAL signatures share one
        array: the signature carries the estimator class, the effective tree
        depth, and every non-scalar param value — so grouped trials differ
        only in the traced scalars of ``_FUSED_SCALAR_PARAMS``."""
        for k in cfg:
            if not self.has_param(k):
                return None

        def val(name):
            return cfg[name] if name in cfg else self.get(name)

        if (val("boosting_type") != "gbdt"
                or val("feature_fraction") < 1.0
                or (val("bagging_fraction") < 1.0 and val("bagging_freq") > 0)
                or val("early_stopping_round") > 0
                or val("validation_indicator_col")
                or val("categorical_slot_indexes")
                or val("monotone_constraints")
                or val("model_string") is not None
                or val("mesh_config") is not None
                # pallas histogram kernel is not vmappable over trials
                or val("histogram_impl") not in ("segment", "onehot")):
            return None
        from .fused import derive_max_depth

        depth = derive_max_depth(val("max_depth"), val("num_leaves"))
        structural = tuple(sorted(
            (name, repr(val(name))) for name in self._param_registry
            if name not in self._FUSED_SCALAR_PARAMS))
        return (type(self).__name__, depth, structural)

    def _fused_trials(self, configs: list[dict]) -> list[dict]:
        return [{fused: self.copy(cfg).get(name) for name, fused
                 in self._FUSED_SCALAR_PARAMS.items()} for cfg in configs]

    # ---- shared helpers ----
    def _features(self, df: DataFrame) -> np.ndarray:
        # float32 sources KEEP float32: that is the multithreaded native
        # binning fast path (BinMapper.transform); everything else widens to
        # float64 (boundary fitting widens internally either way)
        cols = self.get("feature_cols")
        if cols:
            self.require_columns(df, *cols)
            arrs = [np.asarray(df.collect_column(c)) for c in cols]
            dt = (np.float32 if all(a.dtype == np.float32 for a in arrs)
                  else np.float64)
            return np.stack([np.asarray(a, dt) for a in arrs], axis=1)
        fc = self.get("features_col")
        self.require_columns(df, fc)
        col = df.collect_column(fc)
        if col.dtype == object:
            col = np.stack([np.asarray(v) for v in col])
        if col.dtype == np.float32:
            return col
        return np.asarray(col, np.float64)

    def _split_validation(self, df: DataFrame):
        vic = self.get("validation_indicator_col")
        if not vic:
            return df, None
        self.require_columns(df, vic)
        mask = np.asarray(df.collect_column(vic), bool)
        whole = df.collect()
        train = DataFrame([{k: v[~mask] for k, v in whole.items()}])
        valid = DataFrame([{k: v[mask] for k, v in whole.items()}])
        return train, valid

    def _mesh(self):
        cfg = self.get("mesh_config")
        if cfg is None:
            return None
        from ..parallel.mesh import create_mesh

        return create_mesh(cfg).mesh

    def _train_kwargs(self) -> dict:
        return dict(
            num_iterations=self.get("num_iterations"),
            learning_rate=self.get("learning_rate"),
            num_leaves=self.get("num_leaves"),
            max_depth=self.get("max_depth"),
            max_bin=self.get("max_bin"),
            lambda_l1=self.get("lambda_l1"),
            lambda_l2=self.get("lambda_l2"),
            min_data_in_leaf=self.get("min_data_in_leaf"),
            min_sum_hessian=self.get("min_sum_hessian_in_leaf"),
            min_gain_to_split=self.get("min_gain_to_split"),
            feature_fraction=self.get("feature_fraction"),
            bagging_fraction=self.get("bagging_fraction"),
            bagging_freq=self.get("bagging_freq"),
            early_stopping_round=self.get("early_stopping_round"),
            boosting_type=self.get("boosting_type"),
            monotone_constraints=self.get("monotone_constraints"),
            categorical_features=self.get("categorical_slot_indexes"),
            top_rate=self.get("top_rate"), other_rate=self.get("other_rate"),
            drop_rate=self.get("drop_rate"), max_drop=self.get("max_drop"),
            skip_drop=self.get("skip_drop"),
            seed=self.get("seed"),
            histogram_impl=self.get("histogram_impl"),
            init_model=self.get("model_string"),
            verbose=self.get("verbosity") > 0,
            mesh=self._mesh(),
        )


class _LightGBMModelBase(Model, _LightGBMParams):
    booster = ComplexParam("booster", "trained TpuBooster")
    features_shap_col = Param("features_shap_col", "when set, adds per-row "
                              "TreeSHAP contributions (F features + bias; "
                              "reference featuresShap)", default=None)

    def get_booster(self):
        return self.get("booster")

    def get_train_measures(self) -> dict:
        """Per-phase training instrumentation (reference
        ``TaskInstrumentationMeasures``, ``LightGBMPerformance.scala``)."""
        return getattr(self.get_booster(), "train_measures", {})

    def predict_contrib(self, features) -> np.ndarray:
        """Exact TreeSHAP contributions (N, K, F+1) — reference
        ``LightGBMBooster.featuresShap`` surface."""
        b = self.get_booster()
        if not hasattr(b, "predict_contrib"):
            raise NotImplementedError(
                "TreeSHAP contributions need per-node cover statistics, which "
                "boosters imported from LightGBM model strings don't carry; "
                "retrain with this library (or score without features_shap_col)")
        return b.predict_contrib(features)

    def _maybe_shap(self, out: dict, x) -> None:
        col = self.get("features_shap_col")
        if col:
            contrib = self.predict_contrib(x)
            # single-output models emit (N, F+1); multiclass (N, K, F+1)
            out[col] = contrib[:, 0, :] if contrib.shape[1] == 1 else contrib

    def get_feature_importances(self, importance_type: str = "split") -> np.ndarray:
        return self.get_booster().feature_importance(importance_type)

    def save_native_model(self, path: str) -> None:
        """Reference ``saveNativeModel`` — writes the standalone booster dir
        (npz + json) plus ``model.txt`` in LightGBM's own text format, loadable
        by stock LightGBM tooling (booster/LightGBMBooster.scala:458)."""
        import os

        from .interop import to_lightgbm_string

        b = self.get_booster()
        os.makedirs(path, exist_ok=True)
        if hasattr(b, "save"):  # ImportedBooster persists via model.txt only
            b.save(path)
        with open(os.path.join(path, "model.txt"), "w") as f:
            f.write(to_lightgbm_string(b))


# ---------------- classification ----------------

class LightGBMClassifier(Estimator, _LightGBMParams):
    feature_name = "lightgbm"

    objective = Param("objective", "binary | multiclass (auto-detected from labels "
                      "when left at default)", default="auto")
    scale_pos_weight = Param("scale_pos_weight", "positive-class weight "
                             "multiplier (binary)", default=1.0,
                             converter=TypeConverters.to_float)
    is_unbalance = Param("is_unbalance", "auto-weight positives by "
                         "n_neg/n_pos (binary)", default=False,
                         converter=TypeConverters.to_bool)
    probability_col = Param("probability_col", "class probabilities output column",
                            default="probability")
    raw_prediction_col = Param("raw_prediction_col", "raw margin output column",
                               default="rawPrediction")

    def _fit(self, df: DataFrame) -> "LightGBMClassificationModel":
        train, valid = self._split_validation(df)
        x = self._features(train)
        self.require_columns(train, self.get("label_col"))
        y_raw = np.asarray(train.collect_column(self.get("label_col")))
        classes, y = np.unique(y_raw, return_inverse=True)
        num_class = len(classes)
        objective = self.get("objective")
        if objective == "auto":
            objective = "binary" if num_class <= 2 else "multiclass"
        w = (np.asarray(train.collect_column(self.get("weight_col")), np.float32)
             if self.get("weight_col") else None)
        vx = vy = None
        if valid is not None and valid.count() > 0:
            vx = self._features(valid)
            vy = np.searchsorted(classes, np.asarray(valid.collect_column(self.get("label_col"))))

        from .booster import train_booster

        booster = train_booster(
            x, y.astype(np.float32), objective=objective, num_class=num_class,
            weights=w, valid_features=vx, valid_labels=vy,
            scale_pos_weight=self.get("scale_pos_weight"),
            is_unbalance=self.get("is_unbalance"), **self._train_kwargs())
        model = LightGBMClassificationModel(booster=booster, classes=classes)
        model.set(**{k: v for k, v in self._param_values.items()
                     if model.has_param(k)})
        return model

    def _fit_fused(self, df: DataFrame,
                   configs: list[dict]) -> list["LightGBMClassificationModel"]:
        """Fit ``len(configs)`` variants in ONE fused training array
        (``automl.tune`` routes same-signature candidates here). Data is
        featurized/binned once; models come back aligned with ``configs``."""
        work = self.copy(configs[0])
        x = work._features(df)
        work.require_columns(df, work.get("label_col"))
        y_raw = np.asarray(df.collect_column(work.get("label_col")))
        classes, y = np.unique(y_raw, return_inverse=True)
        num_class = len(classes)
        objective = work.get("objective")
        if objective == "auto":
            objective = "binary" if num_class <= 2 else "multiclass"
        n = x.shape[0]
        w = (np.asarray(df.collect_column(work.get("weight_col")), np.float32)
             if work.get("weight_col") else np.ones(n, np.float32))
        from .booster import fold_positive_class_weight, train_boosters_fused

        w = fold_positive_class_weight(
            y.astype(np.float32), w, objective=objective,
            is_unbalance=work.get("is_unbalance"),
            scale_pos_weight=work.get("scale_pos_weight"))

        boosters = train_boosters_fused(
            x, y.astype(np.float32), self._fused_trials(configs),
            objective=objective, num_class=num_class, weights=w,
            max_depth=work.get("max_depth"), max_bin=work.get("max_bin"),
            seed=work.get("seed"),
            histogram_impl=work.get("histogram_impl"))
        models = []
        for cfg, booster in zip(configs, boosters):
            trial_est = self.copy(cfg)
            model = LightGBMClassificationModel(booster=booster,
                                                classes=classes)
            model.set(**{k: v for k, v in trial_est._param_values.items()
                         if model.has_param(k)})
            models.append(model)
        return models


class LightGBMClassificationModel(_LightGBMModelBase):
    feature_name = "lightgbm"

    classes = ComplexParam("classes", "original class labels (argmax index -> label)")
    probability_col = Param("probability_col", "class probabilities output column",
                            default="probability")
    raw_prediction_col = Param("raw_prediction_col", "raw margin output column",
                               default="rawPrediction")

    def _transform(self, df: DataFrame) -> DataFrame:
        b = self.get_booster()
        classes = np.asarray(self.get("classes"))

        def per_part(part):
            sub = DataFrame([part])
            x = self._features(sub)
            # one fused executable for (raw, prob): calling raw_score then
            # predict walked the forest twice and paid two dispatches +
            # transfers per batch — measured 2x per-batch cost on the
            # bulk-scoring hot path
            if hasattr(b, "raw_score_and_predict"):
                raw, prob = b.raw_score_and_predict(x)
            else:  # ImportedBooster et al.
                raw, prob = b.raw_score(x), b.predict(x)
            if b.objective == "binary":
                prob2 = np.stack([1 - prob, prob], axis=1)
                pred_idx = (prob >= 0.5).astype(int)
            else:
                prob2 = prob
                pred_idx = np.argmax(prob, axis=1)
            out = dict(part)
            out[self.get("raw_prediction_col")] = raw
            out[self.get("probability_col")] = prob2
            out[self.get("prediction_col")] = classes[pred_idx]
            self._maybe_shap(out, x)
            return out

        return df.map_partitions(per_part)


# ---------------- regression ----------------

class LightGBMRegressor(Estimator, _LightGBMParams):
    feature_name = "lightgbm"

    objective = Param("objective", "regression | regression_l1 | huber | "
                      "poisson | quantile | tweedie | gamma | mape",
                      default="regression")
    alpha = Param("alpha", "huber delta / quantile level", default=0.9,
                  converter=TypeConverters.to_float)
    tweedie_variance_power = Param(
        "tweedie_variance_power", "tweedie rho in [1, 2): 1 -> poisson limit, "
        "2 -> gamma-like", default=1.5, converter=TypeConverters.to_float)

    def _fit(self, df: DataFrame) -> "LightGBMRegressionModel":
        train, valid = self._split_validation(df)
        x = self._features(train)
        self.require_columns(train, self.get("label_col"))
        y = np.asarray(train.collect_column(self.get("label_col")), np.float32)
        w = (np.asarray(train.collect_column(self.get("weight_col")), np.float32)
             if self.get("weight_col") else None)
        vx = vy = None
        if valid is not None and valid.count() > 0:
            vx = self._features(valid)
            vy = np.asarray(valid.collect_column(self.get("label_col")), np.float32)

        from .booster import train_booster

        booster = train_booster(
            x, y, objective=self.get("objective"), weights=w,
            objective_alpha=self.get("alpha"),
            tweedie_variance_power=self.get("tweedie_variance_power"),
            valid_features=vx, valid_labels=vy, **self._train_kwargs())
        model = LightGBMRegressionModel(booster=booster)
        model.set(**{k: v for k, v in self._param_values.items()
                     if model.has_param(k)})
        return model

    def _fit_fused(self, df: DataFrame,
                   configs: list[dict]) -> list["LightGBMRegressionModel"]:
        """Fused-array twin of ``_fit`` for same-signature sweep candidates
        (see ``LightGBMClassifier._fit_fused``)."""
        work = self.copy(configs[0])
        x = work._features(df)
        work.require_columns(df, work.get("label_col"))
        y = np.asarray(df.collect_column(work.get("label_col")), np.float32)
        w = (np.asarray(df.collect_column(work.get("weight_col")), np.float32)
             if work.get("weight_col") else None)

        from .booster import train_boosters_fused

        boosters = train_boosters_fused(
            x, y, self._fused_trials(configs),
            objective=work.get("objective"), weights=w,
            objective_alpha=work.get("alpha"),
            tweedie_variance_power=work.get("tweedie_variance_power"),
            max_depth=work.get("max_depth"), max_bin=work.get("max_bin"),
            seed=work.get("seed"),
            histogram_impl=work.get("histogram_impl"))
        models = []
        for cfg, booster in zip(configs, boosters):
            trial_est = self.copy(cfg)
            model = LightGBMRegressionModel(booster=booster)
            model.set(**{k: v for k, v in trial_est._param_values.items()
                         if model.has_param(k)})
            models.append(model)
        return models


class LightGBMRegressionModel(_LightGBMModelBase):
    feature_name = "lightgbm"

    def _transform(self, df: DataFrame) -> DataFrame:
        b = self.get_booster()

        def per_part(part):
            sub = DataFrame([part])
            x = self._features(sub)
            out = dict(part)
            out[self.get("prediction_col")] = b.predict(x)
            self._maybe_shap(out, x)
            return out

        return df.map_partitions(per_part)


# ---------------- ranking ----------------

class LightGBMRanker(Estimator, _LightGBMParams):
    feature_name = "lightgbm"

    def _fused_plan(self, cfg: dict):
        return None  # lambdarank's grouped lambda computation is not fusable

    # keep automl.fusable_param_names honest: no fused path, no fusable knobs
    _FUSED_SCALAR_PARAMS: dict = {}

    group_col = Param("group_col", "query/group id column", default="group")
    eval_at = Param("eval_at", "NDCG@k cutoffs", default=(5,),
                    converter=TypeConverters.to_list)

    def _fit(self, df: DataFrame) -> "LightGBMRankerModel":
        train, valid = self._split_validation(df)
        self.require_columns(train, self.get("label_col"), self.get("group_col"))
        # group-contiguous ordering (the reference requires pre-grouped partitions)
        train = train.sort(self.get("group_col"))
        x = self._features(train)
        y = np.asarray(train.collect_column(self.get("label_col")), np.float32)
        gid = np.asarray(train.collect_column(self.get("group_col")))
        _, sizes = np.unique(gid, return_counts=True)
        vx = vy = vsizes = None
        if valid is not None and valid.count() > 0:
            valid = valid.sort(self.get("group_col"))
            vx = self._features(valid)
            vy = np.asarray(valid.collect_column(self.get("label_col")), np.float32)
            _, vsizes = np.unique(np.asarray(valid.collect_column(self.get("group_col"))),
                                  return_counts=True)

        from .booster import train_booster

        booster = train_booster(
            x, y, objective="lambdarank", group_sizes=sizes,
            valid_features=vx, valid_labels=vy, valid_group_sizes=vsizes,
            **self._train_kwargs())
        model = LightGBMRankerModel(booster=booster)
        model.set(**{k: v for k, v in self._param_values.items()
                     if model.has_param(k)})
        return model


class LightGBMRankerModel(_LightGBMModelBase):
    feature_name = "lightgbm"

    def _transform(self, df: DataFrame) -> DataFrame:
        b = self.get_booster()

        def per_part(part):
            sub = DataFrame([part])
            x = self._features(sub)
            out = dict(part)
            out[self.get("prediction_col")] = b.predict(x)
            self._maybe_shap(out, x)
            return out

        return df.map_partitions(per_part)
