"""Horizontally fused GBDT hyperparameter sweeps.

The GBDT twin of :mod:`models.fused_trainer` (HFTA, arXiv:2102.02344):
``N`` trials that share binning (same ``max_bin``) and tree shape (same
effective ``max_depth``) train inside ONE jitted boosting iteration — the
data is binned and device-put once, and each depth level runs ONE fused
histogram build (the :func:`trees._level_histogram` kernel vmapped over the
trial axis) that serves every trial, instead of each trial's own XLA
programs serialized on the device.

Per-trial scalar hyperparameters (``learning_rate``, ``lambda_l1/l2``,
``num_leaves``, ``min_data_in_leaf``, ``min_sum_hessian``,
``min_gain_to_split``) enter the step as traced ``(R,)`` arrays, so the
iteration executable is shared across arbitrary values — the serial path
bakes them into :class:`trees.GrowthConfig` constants and recompiles its
whole level ladder per distinct config. Trial counts bucket to the shared
trial-count ladder (:func:`core.batching.default_trial_bucketer`), padded
slots replay trial 0 and are discarded, so compile counts stay bounded by
the ladder, not by sweep width. Split/leaf math is shared with the serial
path (:func:`trees.level_cum_tables` / :func:`trees.split_gain` /
``trees._leaf_value``), which is what makes fused-vs-serial prediction
parity hold to f32 rounding (``tests/test_fused_automl.py``).

Out of scope (serial fallback in ``automl.tune``): bagging / GOSS / DART /
rf, feature_fraction < 1, categorical features, monotone constraints,
early stopping on a validation set, warm starts.
"""

from __future__ import annotations

import inspect
import time
import types

import jax
import jax.numpy as jnp
import numpy as np

from ..core import batching as cb
from ..core.hpo_metrics import HPO_ARRAY_METRICS as _HPO_METRICS
from .binning import BinMapper
from . import objectives as obj
from . import trees as T

__all__ = ["FUSED_GBDT_SCALARS", "fused_train_boosters"]

# per-trial scalars that become traced step inputs; everything else either
# changes program structure (grouped on it) or is unsupported fused
FUSED_GBDT_SCALARS = ("learning_rate", "lambda_l1", "lambda_l2", "num_leaves",
                      "min_data_in_leaf", "min_sum_hessian",
                      "min_gain_to_split")

def _trial_defaults() -> dict:
    """Unset trial keys fill from serial ``train_booster``'s OWN signature
    defaults, so a direct ``train_boosters_fused`` caller can never get
    silently different hyperparameters than the serial fit it is A/B'd
    against."""
    from .booster import train_booster

    sig = inspect.signature(train_booster)
    return {k: sig.parameters[k].default
            for k in ("num_iterations", *FUSED_GBDT_SCALARS)}


derive_max_depth = T.derive_max_depth


def _level_pass(bins, grad, hess, presence, node_of_row, feature,
                threshold_bin, leaf_value, node_gain, node_cover, leaf_count,
                cfg_ns, base: int, width: int, B: int, hist_impl: str):
    """One depth level for ONE trial (vmapped over trials by the caller):
    the serial ``trees._make_level_step`` math with the per-config constants
    replaced by the traced scalars in ``cfg_ns`` — no categorical /
    monotone / feature-mask branches (those configs take the serial path).
    Selection, budget, and row routing are the SHARED trees helpers, so the
    two paths cannot diverge on them."""
    num_thresholds = B - 1
    hist = T._level_histogram(bins, grad, hess, presence, node_of_row, base,
                              width, B, hist_impl=hist_impl)
    g_tot, h_tot, c_tot, gl, hl, cl = T.level_cum_tables(hist, num_thresholds)
    gr, hr, gain = T.split_gain(g_tot, h_tot, gl, hl, cfg_ns)
    cr = c_tot[:, None, None] - cl
    ok = T.split_ok_mask(cl, cr, hl, hr, cfg_ns)
    gain = jnp.where(ok, gain, -jnp.inf)

    (_best_idx, best_gain, best_feat, best_thr, active,
     do_split) = T.select_level_splits(gain, c_tot, leaf_count, cfg_ns,
                                       width, num_thresholds)

    node_ids = base + jnp.arange(width, dtype=jnp.int32)
    feature = feature.at[node_ids].set(jnp.where(do_split, best_feat, -1))
    threshold_bin = threshold_bin.at[node_ids].set(
        jnp.where(do_split, best_thr, 0))
    value = T._leaf_value(g_tot, h_tot, cfg_ns)
    leaf_value = leaf_value.at[node_ids].set(
        jnp.where(active & ~do_split, value, 0.0))
    node_gain = node_gain.at[node_ids].set(jnp.where(do_split, best_gain, 0.0))
    node_cover = node_cover.at[node_ids].set(c_tot)
    leaf_count = leaf_count + jnp.sum(do_split.astype(jnp.int32))

    _rel, row_split, _f_of_row, _row_bin, go_left = T.level_row_partition(
        bins, node_of_row, do_split, best_feat, best_thr, base, width)
    node_of_row = T.route_rows(node_of_row, row_split, go_left)
    return (node_of_row, feature, threshold_bin, leaf_value, node_gain,
            node_cover, leaf_count)


def _grow_tree_fused(bins, grad, hess, presence, hp: dict, max_depth: int,
                     B: int, hist_impl: str) -> T.TreeArrays:
    """One tree for ONE trial with traced scalar hyperparameters; levels are
    unrolled in-trace (the serial path's per-level jit cache keys on a
    hashable GrowthConfig, which traced scalars are not)."""
    m = T.max_nodes(max_depth)
    feature = jnp.full(m, -1, jnp.int32)
    threshold_bin = jnp.zeros(m, jnp.int32)
    leaf_value = jnp.zeros(m, jnp.float32)
    node_gain = jnp.zeros(m, jnp.float32)
    node_cover = jnp.zeros(m, jnp.float32)
    node_of_row = jnp.zeros(bins.shape[0], jnp.int32)
    leaf_count = jnp.asarray(1, jnp.int32)
    cfg_ns = types.SimpleNamespace(
        lambda_l1=hp["lambda_l1"], lambda_l2=hp["lambda_l2"],
        learning_rate=hp["learning_rate"],
        min_data_in_leaf=hp["min_data_in_leaf"],
        min_sum_hessian=hp["min_sum_hessian"],
        min_gain_to_split=hp["min_gain_to_split"],
        num_leaves=hp["num_leaves"])
    for d in range(max_depth):
        (node_of_row, feature, threshold_bin, leaf_value, node_gain,
         node_cover, leaf_count) = _level_pass(
            bins, grad, hess, presence, node_of_row, feature, threshold_bin,
            leaf_value, node_gain, node_cover, leaf_count, cfg_ns,
            2 ** d - 1, 2 ** d, B, hist_impl)
    # final level: every active node becomes a leaf (per-node totals only)
    base, width = 2 ** max_depth - 1, 2 ** max_depth
    valid = (node_of_row >= base) & (node_of_row < base + width)
    rel = jnp.where(valid, node_of_row - base, 0)
    zero = jnp.zeros_like(grad)
    data = jnp.stack([jnp.where(valid, grad, zero),
                      jnp.where(valid, hess, zero),
                      jnp.where(valid, presence, zero)], axis=-1)
    tot = jax.ops.segment_sum(data, rel, num_segments=width)
    active = tot[:, 2] > 0
    node_ids = base + jnp.arange(width, dtype=jnp.int32)
    value = T._leaf_value(tot[:, 0], tot[:, 1], cfg_ns)
    leaf_value = leaf_value.at[node_ids].set(jnp.where(active, value, 0.0))
    node_cover = node_cover.at[node_ids].set(tot[:, 2])
    return T.TreeArrays(feature, threshold_bin, leaf_value, node_gain,
                        node_cover, jnp.zeros((m, 1), jnp.uint8))


def _build_fused_iteration(o, K: int, max_depth: int, B: int,
                           hist_impl: str):
    """CompiledCache builder: ONE boosting iteration for every trial —
    vmapped grad/hess + K fused trees + score updates in one program."""

    def build():
        def one_trial(scores_t, hp_t, bins, y, presence, w):
            g, h = o.grad_hess(scores_t, y)
            g = g.reshape(scores_t.shape[0], -1)
            h = h.reshape(scores_t.shape[0], -1)
            w_eff = (w * presence)[:, None]
            g = g * w_eff
            h = h * w_eff

            def per_class(sc, gh_k):
                gk, hk, k_idx = gh_k
                tree = _grow_tree_fused(bins, gk, hk, presence, hp_t,
                                        max_depth, B, hist_impl)
                delta = T.traverse_binned(bins, tree, max_depth)
                sc = jax.lax.dynamic_update_index_in_dim(
                    sc, sc[:, k_idx] + delta, k_idx, axis=1)
                return sc, tree

            scores_t, trees = jax.lax.scan(
                per_class, scores_t,
                (jnp.swapaxes(g, 0, 1), jnp.swapaxes(h, 0, 1),
                 jnp.arange(K, dtype=jnp.int32)))
            return scores_t, trees

        fused = jax.vmap(one_trial, in_axes=(0, 0, None, None, None, None))
        return jax.jit(fused, donate_argnums=(0,))

    return build


def fused_train_boosters(features, labels, trials: list[dict], *,
                         objective: str = "regression", num_class: int = 1,
                         max_depth: int = -1, max_bin: int = 255,
                         seed: int = 0, weights=None,
                         objective_alpha: float | None = None,
                         tweedie_variance_power: float | None = None,
                         histogram_impl: str = "segment") -> list:
    """Train ``len(trials)`` boosters in one fused array; returns one
    :class:`booster.TpuBooster` per trial (same scoring surface the serial
    ``train_booster`` produces, sharing one fitted :class:`BinMapper`).

    ``trials``: per-trial overrides of :data:`FUSED_GBDT_SCALARS` plus
    ``num_iterations`` (the array runs to the max; each trial keeps its own
    first ``num_iterations`` trees). All trials must resolve to the same
    effective ``max_depth`` — group by it upstream (``automl.tune`` does).
    """
    from .booster import TpuBooster

    if not trials:
        raise ValueError("fused_train_boosters needs at least one trial")
    defaults = _trial_defaults()
    allowed = set(defaults)
    merged = []
    for i, t in enumerate(trials):
        unknown = set(t) - allowed
        if unknown:
            raise ValueError(
                f"trial {i} has non-fusable keys {sorted(unknown)}; fusable: "
                f"{sorted(allowed)} — route this config to the serial path")
        merged.append({**defaults, **t})
        if merged[-1]["num_iterations"] < 1:
            raise ValueError(f"trial {i}: num_iterations must be >= 1, got "
                             f"{merged[-1]['num_iterations']}")
    depths = {derive_max_depth(max_depth, m["num_leaves"]) for m in merged}
    if len(depths) > 1:
        raise ValueError(
            f"trials resolve to different effective max_depths {sorted(depths)}"
            " — a fused array shares one heap layout; partition by depth "
            "(automl.tune groups on it) or pass max_depth explicitly")
    depth = depths.pop()

    x = np.asarray(features)
    y = np.asarray(labels, np.float32)
    n, f = x.shape
    mapper = BinMapper(max_bin=max_bin, seed=seed)
    bins_np = mapper.fit_transform(x).astype(np.int32)
    B = mapper.num_bins

    obj_kw = {}
    if objective_alpha is not None:
        obj_kw["alpha"] = objective_alpha
    if tweedie_variance_power is not None:
        obj_kw["tweedie_variance_power"] = tweedie_variance_power
    o = obj.get_objective(objective, num_class=num_class, **obj_kw)
    if o.name == "lambdarank":
        raise ValueError("lambdarank sweeps are not fusable (grouped "
                         "lambda computation); use the serial path")
    if o.name in ("poisson", "tweedie", "gamma") and np.any(y < 0):
        raise ValueError(f"{o.name} objective requires non-negative labels")
    K = o.num_model_out
    init = np.asarray(jax.device_get(o.init_score(jnp.asarray(y))),
                      np.float32).reshape(K)

    R = cb.default_trial_bucketer().bucket_for(len(merged))
    slot_trials = list(range(len(merged))) + [0] * (R - len(merged))
    hp = {k: jnp.asarray([merged[t][k] for t in slot_trials],
                         jnp.int32 if k == "num_leaves" else jnp.float32)
          for k in FUSED_GBDT_SCALARS}

    bins = jnp.asarray(bins_np)
    yd = jnp.asarray(y)
    presence = jnp.ones(n, jnp.float32)
    w = jnp.asarray(np.ones(n, np.float32) if weights is None
                    else np.asarray(weights, np.float32))
    scores = jnp.broadcast_to(jnp.asarray(init)[None, None, :],
                              (R, n, K)).astype(jnp.float32)
    scores = jnp.array(scores)  # donation needs an owned buffer

    step = cb.get_compiled_cache().get(
        "gbdt_fused_iter",
        (R, n, f, B, K, depth, histogram_impl, o.name,
         objective_alpha, tweedie_variance_power),
        _build_fused_iteration(o, K, depth, B, histogram_impl))

    m = _HPO_METRICS.get()
    m["active"].set(len(merged), engine="gbdt_fused")
    iters = max(t["num_iterations"] for t in merged)
    acc_f, acc_t, acc_v, acc_g, acc_c = [], [], [], [], []
    t_start = time.perf_counter()
    for _ in range(iters):
        t0 = time.perf_counter()
        scores, trees = step(scores, hp, bins, yd, presence, w)
        acc_f.append(trees.feature)
        acc_t.append(trees.threshold_bin)
        acc_v.append(trees.leaf_value)
        acc_g.append(trees.gain)
        acc_c.append(trees.cover)
        m["step_ms"].observe((time.perf_counter() - t0) * 1e3,
                             engine="gbdt_fused")
        m["steps"].inc(engine="gbdt_fused")
    jax.block_until_ready(acc_f[-1])
    wall = max(time.perf_counter() - t_start, 1e-9)
    m["trials_per_sec"].set(len(merged) * iters / wall, engine="gbdt_fused")

    # ONE host transfer for the whole array: (iters, R, K, M) stacks
    feat_h = np.asarray(jnp.stack(acc_f))
    thr_bin_h = np.asarray(jnp.stack(acc_t))
    val_h = np.asarray(jnp.stack(acc_v))
    gain_h = np.asarray(jnp.stack(acc_g))
    cover_h = np.asarray(jnp.stack(acc_c))
    ub = mapper.upper_bound_values()
    thr_val_h = np.where(feat_h >= 0,
                         ub[np.maximum(feat_h, 0), thr_bin_h],
                         0.0).astype(np.float32)

    out = []
    for i, t in enumerate(merged):
        n_it = t["num_iterations"]
        booster = TpuBooster(
            feat_h[:n_it, i], thr_val_h[:n_it, i], val_h[:n_it, i],
            gain_h[:n_it, i], cover=cover_h[:n_it, i], max_depth=depth,
            num_model_out=K, objective=o.name, init_score=init,
            num_features=f, best_iteration=None,
            params={"num_iterations": n_it,
                    "learning_rate": t["learning_rate"],
                    "num_leaves": t["num_leaves"], "max_bin": max_bin,
                    "boosting_type": "gbdt", "fused": True})
        booster.bin_mapper = mapper
        out.append(booster)
    return out
