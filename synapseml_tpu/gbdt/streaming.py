"""Out-of-core GBDT: histograms built from a streamed source in
fixed-memory passes.

The in-memory path (``booster.train_booster``) keeps the full binned matrix
and running scores resident on device — the right call when the dataset
fits. This module trains when it does NOT: features stream shard-by-shard
from a :class:`synapseml_tpu.data.ShardedSource` and are never materialized
whole. Host memory is bounded by O(shard) for features plus O(n) for the
per-row vectors every out-of-core GBDT keeps (labels, scores, gradients,
node assignment — the n×1 vectors, not the n×F matrix).

Pass structure (the LightGBM out-of-core discipline):

1. **stats pass** — stream shards once: count rows, collect labels, and
   reservoir-sample rows for quantile bin-boundary fitting
   (``BinMapper.fit`` on the sample — fixed memory regardless of n).
2. **bin+spill pass** — stream shards again: bin each shard
   (``BinMapper.transform``) and spill the compact bin codes (uint16, ~2
   bytes/cell vs 4-8 for raw floats) to local ``.npy`` files. Iterations
   then stream the local spill (mmap) instead of re-reading and re-binning
   the source T times.
3. **training** — per boosting iteration, per tree level: stream spilled
   chunks, route each row from its previous-level node, and accumulate the
   ``(nodes, features, bins, 3)`` level histogram on device through the
   same ``trees._level_histogram`` kernel the in-memory engine uses (padded
   fixed-size chunks, so compiles are bounded by tree depth, not data
   size). Split decisions run on the aggregated histogram with the exact
   ``trees.py`` gain/leaf-value math, so streamed and in-memory training
   agree up to float-summation order.

Supported surface (v1): gbdt boosting, numerical features, the scalar/
multiclass objectives. Bagging/GOSS/DART, categorical splits and monotone
constraints stay on the in-memory path.
"""

from __future__ import annotations

import functools
import os
import shutil
import tempfile
import time
from typing import Sequence

import numpy as np

from . import objectives as obj
from .binning import BinMapper
from .booster import TpuBooster

__all__ = ["train_booster_streamed"]

_CHUNK_ROWS = 16384


class _GainCfg:
    """Adapter handing the loose streamed hyper-params to the SHARED
    ``trees.py`` gain/leaf-value formulas — one implementation, so a future
    regularization tweak cannot silently diverge the two engines."""

    def __init__(self, l1, l2, lr):
        self.lambda_l1 = l1
        self.lambda_l2 = l2
        self.learning_rate = lr


def _leaf_value(g, h, l1, l2, lr):
    from .trees import _leaf_value as impl

    return np.asarray(impl(np.asarray(g), np.asarray(h), _GainCfg(l1, l2, lr)))


def _split_score(g, h, l1, l2):
    from .trees import _split_score as impl

    return np.asarray(impl(np.asarray(g), np.asarray(h), _GainCfg(l1, l2, 1.0)))


@functools.lru_cache(maxsize=None)
def _hist_fn(base: int, width: int, num_bins: int):
    """Jitted level-histogram over one fixed-shape chunk — the same
    ``_level_histogram`` kernel the in-memory engine uses; padded rows carry
    ``node_of=-1`` so the in-range mask zeroes them."""
    import jax

    from .trees import _level_histogram

    def f(bins, g, h, presence, node_of):
        return _level_histogram(bins, g, h, presence, node_of, base, width,
                                num_bins)

    return jax.jit(f)


class _Spill:
    """The local binned cache: one .npy per source shard + row offsets."""

    def __init__(self, directory: str, files: list[str], counts: list[int]):
        self.directory = directory
        self.files = files
        self.offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)

    @property
    def n(self) -> int:
        return int(self.offsets[-1])

    def chunks(self, chunk_rows: int):
        """Yield (global_start, bins_chunk) in fixed-memory slices."""
        for f, off in zip(self.files, self.offsets[:-1]):
            mm = np.load(f, mmap_mode="r")
            for lo in range(0, mm.shape[0], chunk_rows):
                hi = min(lo + chunk_rows, mm.shape[0])
                yield int(off + lo), np.asarray(mm[lo:hi])

    def close(self):
        shutil.rmtree(self.directory, ignore_errors=True)


def _feature_matrix(cols: dict, feature_cols: Sequence[str]) -> np.ndarray:
    missing = [c for c in feature_cols if c not in cols]
    if missing:
        raise ValueError(f"shard is missing feature column(s) {missing} "
                         f"(expected {list(feature_cols)}); streamed "
                         "training needs a uniform schema across shards")
    mats = []
    for c in feature_cols:
        v = np.asarray(cols[c])
        mats.append(v[:, None] if v.ndim == 1 else v.reshape(v.shape[0], -1))
    return np.concatenate(mats, axis=1).astype(np.float32, copy=False)


def _grow_tree_streamed(spill: _Spill, g: np.ndarray, h: np.ndarray,
                        node_of: np.ndarray, *, max_depth: int,
                        num_leaves: int, num_bins: int, lambda_l1: float,
                        lambda_l2: float, learning_rate: float,
                        min_data_in_leaf: int, min_sum_hessian: float,
                        min_gain_to_split: float, chunk_rows: int):
    """One tree in heap layout from streamed binned chunks. ``node_of`` is
    the in-memory (n,) row->node vector; on return it holds each row's final
    resting node so the caller can update scores without another pass."""
    import jax.numpy as jnp

    M = 2 ** (max_depth + 1) - 1
    B = num_bins
    feature = np.full(M, -1, np.int32)
    threshold_bin = np.zeros(M, np.int32)
    leaf_value = np.zeros(M, np.float32)
    node_gain = np.zeros(M, np.float32)
    node_cover = np.zeros(M, np.float32)
    leaf_count = 1
    node_of[:] = 0

    def route_chunk(bins_c, lo, hi, base, width):
        """Move rows out of split nodes of level [base, base+width)."""
        nc = node_of[lo:hi]
        here = (nc >= base) & (nc < base + width)
        if not here.any():
            return
        rel = np.where(here, nc - base, 0)
        node_ids = base + rel
        split = here & (feature[node_ids] >= 0)
        f_of = np.maximum(feature[node_ids], 0)
        row_bin = bins_c[np.arange(bins_c.shape[0]), f_of].astype(np.int32)
        go_left = row_bin <= threshold_bin[node_ids]
        node_of[lo:hi] = np.where(split, 2 * nc + np.where(go_left, 1, 2), nc)

    pad_to = max(int(chunk_rows), 1)
    for depth in range(max_depth):
        base, width = 2 ** depth - 1, 2 ** depth
        hist = np.zeros((width, spill_features(spill), B, 3), np.float32)
        hfn = _hist_fn(base, width, B)
        for lo, bins_c in spill.chunks(chunk_rows):
            hi = lo + bins_c.shape[0]
            if depth > 0:
                route_chunk(bins_c, lo, hi, 2 ** (depth - 1) - 1,
                            2 ** (depth - 1))
            c = bins_c.shape[0]
            pad = pad_to - c
            bpad = np.pad(bins_c, ((0, pad), (0, 0))) if pad else bins_c
            nof = np.pad(node_of[lo:hi], (0, pad), constant_values=-1) \
                if pad else node_of[lo:hi]
            gp = np.pad(g[lo:hi], (0, pad)) if pad else g[lo:hi]
            hp = np.pad(h[lo:hi], (0, pad)) if pad else h[lo:hi]
            pres = np.zeros(pad_to, np.float32)
            pres[:c] = 1.0
            hist += np.asarray(hfn(jnp.asarray(bpad.astype(np.int32)),
                                   jnp.asarray(gp), jnp.asarray(hp),
                                   jnp.asarray(pres), jnp.asarray(nof)))

        # -- split decision on the aggregated histogram (trees.py math) ----
        cum = hist.cumsum(axis=2)                      # (W, F, B, 3)
        total = cum[:, 0, -1, :]                       # (W, 3)
        g_tot, h_tot, c_tot = total[:, 0], total[:, 1], total[:, 2]
        nt = B - 1  # thresholds 0..B-2 (NaN bin never a left-inclusive cut)
        gl = cum[:, :, :nt, 0]
        hl = cum[:, :, :nt, 1]
        cl = cum[:, :, :nt, 2]
        gr = g_tot[:, None, None] - gl
        hr = h_tot[:, None, None] - hl
        cr = c_tot[:, None, None] - cl
        gain = (_split_score(gl, hl, lambda_l1, lambda_l2)
                + _split_score(gr, hr, lambda_l1, lambda_l2)
                - _split_score(g_tot, h_tot, lambda_l1,
                               lambda_l2)[:, None, None])
        ok = ((cl >= min_data_in_leaf) & (cr >= min_data_in_leaf)
              & (hl >= min_sum_hessian) & (hr >= min_sum_hessian))
        gain = np.where(ok, gain, -np.inf)
        flat = gain.reshape(width, -1)
        best_idx = np.argmax(flat, axis=1)
        best_gain = flat[np.arange(width), best_idx]
        best_feat = (best_idx // nt).astype(np.int32)
        best_thr = (best_idx % nt).astype(np.int32)
        active = c_tot > 0
        can_split = active & (best_gain > min_gain_to_split)
        budget = max(num_leaves - leaf_count, 0)
        order = np.argsort(np.where(can_split, -best_gain, np.inf),
                           kind="stable")
        rank = np.zeros(width, np.int32)
        rank[order] = np.arange(width, dtype=np.int32)
        do_split = can_split & (rank < budget)

        node_ids = base + np.arange(width)
        feature[node_ids] = np.where(do_split, best_feat, -1)
        threshold_bin[node_ids] = np.where(do_split, best_thr, 0)
        value = _leaf_value(g_tot, h_tot, lambda_l1, lambda_l2, learning_rate)
        leaf_value[node_ids] = np.where(active & ~do_split, value, 0.0)
        node_gain[node_ids] = np.where(do_split, best_gain, 0.0)
        node_cover[node_ids] = c_tot
        leaf_count += int(do_split.sum())

    # final routing pass (into level max_depth) + leaf totals, no bins needed
    # beyond the routing read
    last_base, last_width = 2 ** (max_depth - 1) - 1, 2 ** (max_depth - 1)
    if max_depth > 0:
        for lo, bins_c in spill.chunks(chunk_rows):
            route_chunk(bins_c, lo, lo + bins_c.shape[0], last_base,
                        last_width)
    fbase, fwidth = 2 ** max_depth - 1, 2 ** max_depth
    at_final = (node_of >= fbase)
    if at_final.any():
        rel = node_of[at_final] - fbase
        gt = np.bincount(rel, weights=g[at_final], minlength=fwidth)
        ht = np.bincount(rel, weights=h[at_final], minlength=fwidth)
        ct = np.bincount(rel, minlength=fwidth).astype(np.float32)
        ids = fbase + np.arange(fwidth)
        vals = _leaf_value(gt, ht, lambda_l1, lambda_l2, learning_rate)
        leaf_value[ids] = np.where(ct > 0, vals, 0.0).astype(np.float32)
        node_cover[ids] = ct
    return feature, threshold_bin, leaf_value, node_gain, node_cover


def spill_features(spill: _Spill) -> int:
    if not hasattr(spill, "_n_features"):
        spill._n_features = np.load(spill.files[0], mmap_mode="r").shape[1]
    return spill._n_features


def train_booster_streamed(source, *, label_col: str = "label",
                           feature_cols: Sequence[str] | None = None,
                           objective: str = "regression", num_class: int = 1,
                           num_iterations: int = 50,
                           learning_rate: float = 0.1, num_leaves: int = 31,
                           max_depth: int = 6, max_bin: int = 255,
                           lambda_l1: float = 0.0, lambda_l2: float = 0.0,
                           min_data_in_leaf: int = 20,
                           min_sum_hessian: float = 1e-3,
                           min_gain_to_split: float = 0.0, seed: int = 0,
                           sample_rows: int = 200_000,
                           spill_dir: str | None = None,
                           chunk_rows: int = _CHUNK_ROWS,
                           measures=None) -> TpuBooster:
    """Train a :class:`TpuBooster` from a streamed source (see module
    docstring for the pass structure and the supported surface)."""
    import jax
    import jax.numpy as jnp

    if objective == "lambdarank":
        raise ValueError("lambdarank needs group structure and stays on the "
                         "in-memory path (train_booster)")
    if measures is None:
        from ..core.instrumentation import InstrumentationMeasures

        measures = InstrumentationMeasures()
    from ..core import observability as _obs

    step_hist = _obs.get_registry().histogram(
        "synapseml_train_step_duration_ms",
        "training step (boosting iteration / optimizer step) wall time",
        ("engine",)).labels(engine="gbdt_streamed")
    if max_depth is None or int(max_depth) <= 0:
        # the in-memory engine's convention: <=0 means derive a heap-layout
        # bound deep enough for num_leaves (booster.py does the same) —
        # clamping -1 to 1 would silently train depth-1 stumps
        max_depth = max(int(np.ceil(np.log2(max(num_leaves, 2)))) + 1, 3)
    max_depth = min(int(max_depth), 10)

    # -- pass 1: row count, labels, reservoir sample ------------------------
    rng = np.random.default_rng(seed)
    reservoir: np.ndarray | None = None
    labels: list[np.ndarray] = []
    counts: list[int] = []
    seen = 0
    inferred_cols = feature_cols is None
    with measures.measure("stats_pass"):
        for shard, cols in source.iter_shards():
            if not cols:
                # degenerate byte-range shard (no complete line): both
                # passes must agree it holds zero rows so spill files stay
                # aligned with the recorded counts
                counts.append(0)
                continue
            if label_col not in cols:
                raise ValueError(
                    f"shard {shard.target} has no label column "
                    f"{label_col!r} (columns: {sorted(cols)}); pass "
                    "label_col= for this dataset")
            if feature_cols is None:
                feature_cols = sorted(k for k in cols if k != label_col)
            elif inferred_cols:
                # inferred from shard 0: a LATER shard introducing extra
                # keys would otherwise be silently excluded from the model
                extra = sorted(k for k in cols
                               if k != label_col and k not in feature_cols)
                if extra:
                    raise ValueError(
                        f"shard {shard.target} carries column(s) {extra} "
                        f"absent from the first shard (inferred features "
                        f"{list(feature_cols)}); schema drifts across "
                        "shards — pass feature_cols= explicitly")
            feats = _feature_matrix(cols, feature_cols)
            labels.append(np.asarray(cols[label_col], np.float32))
            counts.append(feats.shape[0])
            if reservoir is None:
                reservoir = np.empty((0, feats.shape[1]), np.float32)
            room = sample_rows - reservoir.shape[0]
            if room > 0:
                reservoir = np.concatenate([reservoir, feats[:room]])
                feats = feats[room:]
            if feats.shape[0]:
                # Algorithm R (vectorized): row with global index i draws
                # j ~ U[0, i]; j < capacity replaces slot j — uniform sample
                # over every row seen so far
                pos = sample_rows + seen + np.arange(feats.shape[0])
                draw = rng.integers(0, pos + 1)
                take = draw < sample_rows
                reservoir[draw[take]] = feats[take]
                seen += feats.shape[0]
    y = np.concatenate(labels) if labels else np.empty(0, np.float32)
    n = y.shape[0]
    if n == 0:
        raise ValueError("streamed training needs at least one row")

    # -- pass 2: fit bins on the sample, spill binned shards ----------------
    mapper = BinMapper(max_bin=max_bin, seed=seed)
    with measures.measure("binning"):
        mapper.fit(reservoir)
        directory = spill_dir or tempfile.mkdtemp(prefix="synapseml_gbdt_")
        os.makedirs(directory, exist_ok=True)
        files = []
        n_features = reservoir.shape[1] if reservoir is not None else 0
        for i, (shard, cols) in enumerate(source.iter_shards()):
            feats = (_feature_matrix(cols, feature_cols) if cols
                     else np.empty((0, n_features), np.float32))
            path = os.path.join(directory, f"binned_{i:05d}.npy")
            np.save(path, mapper.transform(feats).astype(np.uint16))
            files.append(path)
    spill = _Spill(directory, files, counts)

    # -- training -----------------------------------------------------------
    o = obj.get_objective(objective, num_class=num_class)
    K = o.num_model_out
    init = np.asarray(jax.device_get(o.init_score(jnp.asarray(y))),
                      np.float32).reshape(K)
    scores = np.broadcast_to(init[None, :], (n, K)).copy()

    grad_hess = jax.jit(lambda s, yv: o.grad_hess(s, yv))
    node_of = np.zeros(n, np.int32)
    M = 2 ** (max_depth + 1) - 1
    acc_f = np.empty((num_iterations, K, M), np.int32)
    acc_t = np.empty((num_iterations, K, M), np.int32)
    acc_v = np.empty((num_iterations, K, M), np.float32)
    acc_g = np.empty((num_iterations, K, M), np.float32)
    acc_c = np.empty((num_iterations, K, M), np.float32)
    grow_kw = dict(max_depth=max_depth, num_leaves=num_leaves,
                   num_bins=mapper.num_bins, lambda_l1=lambda_l1,
                   lambda_l2=lambda_l2, learning_rate=learning_rate,
                   min_data_in_leaf=min_data_in_leaf,
                   min_sum_hessian=min_sum_hessian,
                   min_gain_to_split=min_gain_to_split,
                   chunk_rows=chunk_rows)
    try:
        for it in range(num_iterations):
            t_iter = time.perf_counter()
            measures.count("iterations")
            with measures.measure("training"):
                gk, hk = (np.asarray(a, np.float32).reshape(n, -1)
                          for a in grad_hess(jnp.asarray(scores),
                                             jnp.asarray(y)))
                for k in range(K):
                    (acc_f[it, k], acc_t[it, k], acc_v[it, k], acc_g[it, k],
                     acc_c[it, k]) = _grow_tree_streamed(
                        spill, gk[:, k], hk[:, k], node_of, **grow_kw)
                    scores[:, k] += acc_v[it, k][node_of]
            step_hist.observe((time.perf_counter() - t_iter) * 1e3)
    finally:
        if spill_dir is None:
            spill.close()

    ub = mapper.upper_bound_values()
    thr_val = np.where(acc_f >= 0, ub[np.maximum(acc_f, 0), acc_t],
                       0.0).astype(np.float32)
    booster = TpuBooster(
        acc_f, thr_val, acc_v, acc_g, cover=acc_c, max_depth=max_depth,
        num_model_out=K, objective=o.name, init_score=init,
        num_features=int(reservoir.shape[1]),
        params={"num_iterations": num_iterations,
                "learning_rate": learning_rate, "num_leaves": num_leaves,
                "max_bin": max_bin, "streamed": True})
    booster.bin_mapper = mapper
    booster.train_measures = measures.to_dict()
    return booster
