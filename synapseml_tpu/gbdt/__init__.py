"""TPU-native gradient-boosted decision trees (the LightGBM-equivalent engine).

Replaces the reference's LightGBM C++/SWIG stack (``lightgbm/`` module,
SURVEY.md §2.1): the native histogram builder + socket-ring allreduce
(``NetworkManager.scala`` → ``LGBM_NetworkInit``) become a batched XLA
histogram (``segment_sum`` per depth level) with an ICI ``psum`` over the
``data`` mesh axis; tree growth is vectorized split evaluation on device.
"""

from .binning import BinMapper
from .booster import TpuBooster, train_booster_from_source, train_boosters_fused
from .interop import ImportedBooster, parse_lightgbm_string, to_lightgbm_string
from .estimators import (
    LightGBMClassificationModel,
    LightGBMClassifier,
    LightGBMRanker,
    LightGBMRankerModel,
    LightGBMRegressionModel,
    LightGBMRegressor,
)

__all__ = [
    "BinMapper",
    "TpuBooster",
    "ImportedBooster",
    "parse_lightgbm_string",
    "to_lightgbm_string",
    "LightGBMClassifier",
    "LightGBMClassificationModel",
    "LightGBMRegressor",
    "LightGBMRegressionModel",
    "LightGBMRanker",
    "LightGBMRankerModel",
    "train_booster_from_source",
    "train_boosters_fused",
]
