"""Boosting objectives: gradients/hessians + eval metrics, all jittable.

Reference analog: LightGBM's native objective functions selected via the
``objective`` train param (``params/LightGBMParams.scala``; the classifier
forces binary/multiclass, ``LightGBMClassifier.scala:212`` area) and the
metric evaluation used for early stopping (``TrainUtils.scala:98-222``).

LambdaRank is the padded-group TPU formulation: groups are padded to the max
group size so the pairwise lambda computation is one dense (G, S, S) batch —
no ragged loops, MXU-friendly.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Objective", "get_objective", "pad_groups", "lambdarank_grad_hess", "ndcg_at_k"]


class Objective(NamedTuple):
    name: str
    num_model_out: int  # trees grown per boosting iteration (K for multiclass)
    init_score: Callable  # labels -> (K,) initial raw score
    grad_hess: Callable  # (scores (N,K), labels (N,)) -> (grad (N,K), hess (N,K))
    transform: Callable  # raw scores (N,K) -> predictions (prob etc.)
    metric: Callable  # (scores (N,K), labels (N,)) -> scalar (lower is better)
    metric_name: str


def _sigmoid(x):
    return jax.nn.sigmoid(x)


# ---------------- regression ----------------

def _reg_init(y):
    return jnp.mean(y)[None]


def _l2_grad_hess(s, y):
    return s[:, 0] - y, jnp.ones_like(y)


def _l1_grad_hess(s, y):
    return jnp.sign(s[:, 0] - y), jnp.ones_like(y)


def _huber_grad_hess(s, y, delta=1.0):
    r = s[:, 0] - y
    return jnp.clip(r, -delta, delta), jnp.ones_like(y)


def _poisson_grad_hess(s, y):
    mu = jnp.exp(s[:, 0])
    return mu - y, mu


def _quantile_grad_hess(s, y, alpha=0.5):
    r = s[:, 0] - y
    return jnp.where(r >= 0, 1.0 - alpha, -alpha), jnp.ones_like(y)


def _gamma_grad_hess(s, y):
    # gamma deviance with log link (LightGBM RegressionGammaLoss):
    # grad = 1 - y e^{-s}, hess = y e^{-s}
    e = y * jnp.exp(-s[:, 0])
    return 1.0 - e, e


def _mape_grad_hess(s, y):
    # mean absolute percentage error: |r|/max(|y|,1) with L1-style grad;
    # the per-row 1/|y| factor rides the HESSIAN-side weight like LightGBM
    w = 1.0 / jnp.maximum(jnp.abs(y), 1.0)
    r = s[:, 0] - y
    return jnp.sign(r) * w, w


def _tweedie_grad_hess(s, y, rho=1.5):
    # LightGBM tweedie (1 <= rho < 2, log link): deviance
    # -y e^{(1-rho)s}/(1-rho) + e^{(2-rho)s}/(2-rho); d/ds and d2/ds2
    a = jnp.exp((1.0 - rho) * s[:, 0])
    b = jnp.exp((2.0 - rho) * s[:, 0])
    grad = -y * a + b
    hess = -y * (1.0 - rho) * a + (2.0 - rho) * b
    return grad, hess


def _rmse(s, y):
    return jnp.sqrt(jnp.mean((s[:, 0] - y) ** 2))


def _rmse_exp_link(s, y):
    # log-link objectives (poisson/tweedie) carry raw scores on the LOG
    # scale; the validation metric must compare on the mean scale or early
    # stopping optimizes a wrong-scale number
    return jnp.sqrt(jnp.mean((jnp.exp(s[:, 0]) - y) ** 2))


def _log_mean_init(y):
    return jnp.log(jnp.maximum(jnp.mean(y), 1e-6))[None]


def _mae(s, y):
    return jnp.mean(jnp.abs(s[:, 0] - y))


# ---------------- binary ----------------

def _binary_init(y):
    p = jnp.clip(jnp.mean(y), 1e-6, 1 - 1e-6)
    return jnp.log(p / (1 - p))[None]


def _binary_grad_hess(s, y):
    p = _sigmoid(s[:, 0])
    return p - y, p * (1 - p)


def _binary_logloss(s, y):
    p = jnp.clip(_sigmoid(s[:, 0]), 1e-12, 1 - 1e-12)
    return -jnp.mean(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))


# ---------------- multiclass ----------------

def _multi_init(y, k):
    counts = jnp.bincount(y.astype(jnp.int32), length=k) + 1.0
    return jnp.log(counts / counts.sum())


def _multi_grad_hess(s, y, k):
    p = jax.nn.softmax(s, axis=1)
    onehot = jax.nn.one_hot(y.astype(jnp.int32), k)
    return p - onehot, p * (1 - p)


def _multi_logloss(s, y, k):
    p = jnp.clip(jax.nn.softmax(s, axis=1), 1e-12, 1.0)
    return -jnp.mean(jnp.log(jnp.take_along_axis(p, y.astype(jnp.int32)[:, None], axis=1)[:, 0]))


# ---------------- lambdarank ----------------

def pad_groups(group_sizes: np.ndarray) -> tuple[np.ndarray, int]:
    """Row → (group, slot) scatter indices for padding ragged groups to (G, S)."""
    sizes = np.asarray(group_sizes, dtype=np.int64)
    max_size = int(sizes.max()) if sizes.size else 1
    rows = []
    for g, sz in enumerate(sizes):
        for s in range(sz):
            rows.append((g, s))
    return np.asarray(rows, dtype=np.int32), max_size


def lambdarank_grad_hess(scores: jax.Array, labels: jax.Array, group_slot: jax.Array,
                         num_groups: int, max_size: int, sigma: float = 1.0):
    """Pairwise LambdaMART gradients with |ΔNDCG| weighting, on padded groups.

    scores/labels: (N,) row-aligned; group_slot (N, 2) → padded (G, S) dense.
    """
    n = scores.shape[0]
    pad_s = jnp.full((num_groups, max_size), -jnp.inf).at[group_slot[:, 0], group_slot[:, 1]].set(scores)
    pad_y = jnp.zeros((num_groups, max_size)).at[group_slot[:, 0], group_slot[:, 1]].set(labels)
    valid = jnp.zeros((num_groups, max_size), bool).at[group_slot[:, 0], group_slot[:, 1]].set(True)

    gain = (2.0 ** pad_y - 1.0) * valid
    # rank by score within group (descending); invalid slots sink with -inf
    order = jnp.argsort(-pad_s, axis=1)
    rank_of = jnp.zeros_like(order).at[jnp.arange(num_groups)[:, None], order].set(
        jnp.arange(max_size)[None, :])
    discount = 1.0 / jnp.log2(rank_of + 2.0)

    # ideal DCG per group for normalization
    ideal_order = jnp.argsort(-pad_y - valid * 0.0 + jnp.where(valid, 0.0, -jnp.inf), axis=1)
    ideal_gain = jnp.take_along_axis(gain, ideal_order, axis=1)
    idcg = jnp.sum(ideal_gain / jnp.log2(jnp.arange(max_size)[None, :] + 2.0), axis=1)
    idcg = jnp.maximum(idcg, 1e-12)

    sdiff = pad_s[:, :, None] - pad_s[:, None, :]  # (G, S, S)
    ydiff = pad_y[:, :, None] - pad_y[:, None, :]
    pair_valid = valid[:, :, None] & valid[:, None, :] & (ydiff > 0)
    # |ΔNDCG| of swapping i and j
    dgain = jnp.abs((gain[:, :, None] - gain[:, None, :])
                    * (discount[:, :, None] - discount[:, None, :])) / idcg[:, None, None]
    rho = jax.nn.sigmoid(-sigma * sdiff)  # P(j beats i) given i should rank higher
    lam = jnp.where(pair_valid, sigma * rho * dgain, 0.0)
    hpair = jnp.where(pair_valid, sigma * sigma * rho * (1 - rho) * dgain, 0.0)

    g_pad = -jnp.sum(lam, axis=2) + jnp.sum(jnp.swapaxes(lam, 1, 2), axis=2)
    h_pad = jnp.sum(hpair, axis=2) + jnp.sum(jnp.swapaxes(hpair, 1, 2), axis=2)
    grad = g_pad[group_slot[:, 0], group_slot[:, 1]]
    hess = jnp.maximum(h_pad[group_slot[:, 0], group_slot[:, 1]], 1e-6)
    return grad.reshape(n), hess.reshape(n)


def ndcg_at_k(scores: jax.Array, labels: jax.Array, group_slot: jax.Array,
              num_groups: int, max_size: int, k: int = 10) -> jax.Array:
    pad_s = jnp.full((num_groups, max_size), -jnp.inf).at[group_slot[:, 0], group_slot[:, 1]].set(scores)
    pad_y = jnp.zeros((num_groups, max_size)).at[group_slot[:, 0], group_slot[:, 1]].set(labels)
    valid = jnp.zeros((num_groups, max_size), bool).at[group_slot[:, 0], group_slot[:, 1]].set(True)
    gain = (2.0 ** pad_y - 1.0) * valid
    topk = min(k, max_size)
    disc = 1.0 / jnp.log2(jnp.arange(topk) + 2.0)
    order = jnp.argsort(-pad_s, axis=1)[:, :topk]
    dcg = jnp.sum(jnp.take_along_axis(gain, order, axis=1) * disc[None, :], axis=1)
    iorder = jnp.argsort(jnp.where(valid, -pad_y, jnp.inf), axis=1)[:, :topk]
    idcg = jnp.sum(jnp.take_along_axis(gain, iorder, axis=1) * disc[None, :], axis=1)
    return jnp.mean(dcg / jnp.maximum(idcg, 1e-12))


# ---------------- registry ----------------

def get_objective(name: str, num_class: int = 1, **kw) -> Objective:
    name = name.lower()
    if name in ("regression", "regression_l2", "l2", "mse", "rmse"):
        return Objective("regression", 1, _reg_init,
                         lambda s, y: _l2_grad_hess(s, y),
                         lambda s: s[:, 0], _rmse, "rmse")
    if name in ("regression_l1", "l1", "mae"):
        return Objective("regression_l1", 1,
                         lambda y: jnp.median(y)[None],
                         lambda s, y: _l1_grad_hess(s, y),
                         lambda s: s[:, 0], _mae, "mae")
    if name == "huber":
        delta = float(kw.get("alpha", 1.0))
        return Objective("huber", 1, _reg_init,
                         lambda s, y: _huber_grad_hess(s, y, delta),
                         lambda s: s[:, 0], _rmse, "rmse")
    if name == "poisson":
        return Objective("poisson", 1, _log_mean_init, _poisson_grad_hess,
                         lambda s: jnp.exp(s[:, 0]), _rmse_exp_link, "rmse")
    if name == "quantile":
        alpha = float(kw.get("alpha", 0.5))
        return Objective("quantile", 1,
                         lambda y: jnp.quantile(y, alpha)[None],
                         lambda s, y: _quantile_grad_hess(s, y, alpha),
                         lambda s: s[:, 0], _mae, "mae")
    if name == "gamma":
        return Objective("gamma", 1, _log_mean_init, _gamma_grad_hess,
                         lambda s: jnp.exp(s[:, 0]), _rmse_exp_link, "rmse")
    if name == "mape":
        def _mape_init(y):
            # MAPE's optimum is the 1/max(|y|,1)-WEIGHTED median — starting
            # from the plain median leaves slow constant-hessian updates a
            # long way to travel on skewed targets (LightGBM inits from the
            # weighted percentile too)
            w = 1.0 / jnp.maximum(jnp.abs(y), 1.0)
            order = jnp.argsort(y)
            cw = jnp.cumsum(w[order])
            idx = jnp.searchsorted(cw, cw[-1] / 2.0)
            return y[order][jnp.minimum(idx, y.shape[0] - 1)][None]

        return Objective("mape", 1, _mape_init, _mape_grad_hess,
                         lambda s: s[:, 0],
                         lambda s, y: jnp.mean(jnp.abs(s[:, 0] - y)
                                               / jnp.maximum(jnp.abs(y), 1.0)),
                         "mape")
    if name == "tweedie":
        rho = float(kw.get("tweedie_variance_power", 1.5))
        if not 1.0 <= rho < 2.0:  # LightGBM's bound; rho=1 = poisson limit
            raise ValueError(
                f"tweedie_variance_power must be in [1, 2), got {rho}")
        return Objective("tweedie", 1, _log_mean_init,
                         lambda s, y: _tweedie_grad_hess(s, y, rho),
                         lambda s: jnp.exp(s[:, 0]), _rmse_exp_link, "rmse")
    if name == "binary":
        return Objective("binary", 1, _binary_init, _binary_grad_hess,
                         lambda s: _sigmoid(s[:, 0]), _binary_logloss, "binary_logloss")
    if name in ("multiclass", "softmax"):
        k = int(num_class)
        if k < 2:
            raise ValueError("multiclass requires num_class >= 2")
        return Objective("multiclass", k,
                         lambda y: _multi_init(y, k),
                         lambda s, y: _multi_grad_hess(s, y, k),
                         lambda s: jax.nn.softmax(s, axis=1),
                         lambda s, y: _multi_logloss(s, y, k), "multi_logloss")
    if name == "lambdarank":
        # grad_hess is bound by the booster once group structure is known
        return Objective("lambdarank", 1, lambda y: jnp.zeros(1),
                         None, lambda s: s[:, 0], None, "ndcg")
    raise ValueError(f"unknown objective {name!r}")
