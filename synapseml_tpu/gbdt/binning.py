"""Feature quantile binning — host-side prep for the on-device histogram trees.

Reference analog: LightGBM's ``BinMapper``/``Dataset`` construction, reached via
the streaming data-transfer path (``StreamingPartitionTask.scala:17-96``,
``LGBM_DatasetPushRowsWithMetadata``) with the sampled bin-boundary step in
``dataset/SampledData.scala``. Here binning produces a dense ``uint8``/``int32``
matrix that moves to HBM once and stays there for the whole boosting run —
the TPU-native replacement for LightGBM's native Dataset memory.

Missing values (NaN) get their own reserved bin (the last one), mirroring
LightGBM's ``use_missing`` default behavior.
"""

from __future__ import annotations

import numpy as np

__all__ = ["BinMapper"]


class BinMapper:
    """Per-feature quantile bin boundaries fit on a sample of rows.

    ``max_bin`` counts real-value bins; one extra bin is reserved for NaN, so
    binned codes live in ``[0, max_bin]`` and the histogram width is
    ``max_bin + 1``.
    """

    def __init__(self, max_bin: int = 255, sample_count: int = 200_000, seed: int = 0,
                 categorical: tuple = ()):
        if not 2 <= max_bin <= 65535:
            raise ValueError(f"max_bin must be in [2, 65535], got {max_bin}")
        self.max_bin = int(max_bin)
        self.sample_count = int(sample_count)
        self.seed = int(seed)
        # categorical feature indices bin by IDENTITY: the category code is
        # the bin (codes outside [0, max_bin) and NaN -> the NaN bin, which
        # routes right — LightGBM's unseen-category behavior)
        self.categorical = tuple(int(i) for i in categorical)
        self.boundaries_: np.ndarray | None = None  # (F, max_bin - 1) float64

    @property
    def num_bins(self) -> int:
        return self.max_bin + 1  # + NaN bin

    @property
    def nan_bin(self) -> int:
        return self.max_bin

    def fit(self, features: np.ndarray) -> "BinMapper":
        x = np.asarray(features, dtype=np.float64)
        if x.ndim != 2:
            raise ValueError(f"features must be 2-D, got shape {x.shape}")
        n, f = x.shape
        if n > self.sample_count:
            rng = np.random.default_rng(self.seed)
            x = x[rng.choice(n, self.sample_count, replace=False)]
        qs = np.linspace(0.0, 1.0, self.max_bin + 1)[1:-1]
        bounds = np.empty((f, self.max_bin - 1), dtype=np.float64)
        for j in range(f):
            col = x[:, j]
            col = col[~np.isnan(col)]
            if col.size == 0:
                bounds[j] = 0.0
                continue
            # unique-aware boundaries: few distinct values -> one bin per value,
            # like LightGBM's FindBinWithZeroAsOneBin for low-cardinality features
            uniq = np.unique(col)
            if uniq.size <= self.max_bin:
                mids = (uniq[:-1] + uniq[1:]) / 2.0
                pad = np.full(self.max_bin - 1 - mids.size, np.inf)
                bounds[j] = np.concatenate([mids, pad])
            else:
                bounds[j] = np.quantile(col, qs, method="linear")
        self.boundaries_ = bounds
        return self

    def transform(self, features: np.ndarray) -> np.ndarray:
        """Rows → bin codes, shape (N, F), dtype int32 (uint8 when it fits).

        float32 inputs take the multithreaded native row-major path
        (``native.bin_rows`` — the Dataset-marshaling hot loop; exact parity:
        double(float32) is lossless, so comparisons match the numpy float64
        path bit-for-bit). Other dtypes, and toolchain-less hosts, use the
        numpy per-column fallback.
        """
        if self.boundaries_ is None:
            raise RuntimeError("BinMapper not fitted")
        arr = np.asarray(features)
        n, f = arr.shape
        if f != self.boundaries_.shape[0]:
            raise ValueError(f"feature count {f} != fitted {self.boundaries_.shape[0]}")
        if self.categorical and not all(0 <= i < f for i in self.categorical):
            # both paths must agree; a negative index would identity-bin on
            # the native path but quantile-bin on the numpy path
            raise ValueError(f"categorical indices {sorted(self.categorical)} "
                             f"out of range [0, {f})")
        out = None
        if arr.dtype == np.float32:
            from .. import native

            out = native.bin_rows(arr, self.boundaries_, self.nan_bin,
                                  self.max_bin, self.categorical)
        if out is None:
            x = np.asarray(arr, dtype=np.float64)  # no-op view for f64 input
            out = np.empty((n, f), dtype=np.int32)
            cat = set(self.categorical)
            for j in range(f):
                if j in cat:
                    col = x[:, j]
                    code = np.floor(col)
                    valid = np.isfinite(col) & (code >= 0) & (code < self.max_bin)
                    out[:, j] = np.where(valid, code, self.nan_bin).astype(np.int32)
                else:
                    out[:, j] = np.searchsorted(self.boundaries_[j], x[:, j],
                                                side="right")
            nan_mask = np.isnan(x)
            if nan_mask.any():
                out[nan_mask] = self.nan_bin  # no-op for cat cols (already set)
        if self.num_bins <= 256:
            return out.astype(np.uint8)
        return out

    def fit_transform(self, features: np.ndarray) -> np.ndarray:
        return self.fit(features).transform(features)

    def upper_bound_values(self) -> np.ndarray:
        """(F, num_bins) real-valued upper edge per bin — lets a trained booster
        predict from raw floats without the mapper (thresholds stored as values,
        the same trick LightGBM model files use)."""
        if self.boundaries_ is None:
            raise RuntimeError("BinMapper not fitted")
        f = self.boundaries_.shape[0]
        ub = np.full((f, self.num_bins), np.inf)
        ub[:, : self.max_bin - 1] = self.boundaries_
        return ub

    def to_dict(self) -> dict:
        return {
            "max_bin": self.max_bin,
            "sample_count": self.sample_count,
            "seed": self.seed,
            "categorical": list(self.categorical),
            "boundaries": None if self.boundaries_ is None else self.boundaries_.tolist(),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BinMapper":
        m = cls(d["max_bin"], d["sample_count"], d["seed"],
                categorical=tuple(d.get("categorical", ())))
        if d.get("boundaries") is not None:
            m.boundaries_ = np.asarray(d["boundaries"], dtype=np.float64)
        return m
