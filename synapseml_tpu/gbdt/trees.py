"""On-device histogram tree growth — the TPU replacement for LightGBM's C++ core.

Reference analog: the native hot loop behind ``LGBM_BoosterUpdateOneIter``
(``booster/LightGBMBooster.scala:355``, ``TrainUtils.scala:98``): histogram
construction + allreduce + best-split + partition. The TPU-native redesign:

  * Trees live in fixed-size heap-layout arrays (node ``i`` → children
    ``2i+1``/``2i+2``): static shapes, so every step jits once per depth level
    and is reused across all trees and boosting iterations.
  * Growth is **level-wise**: one batched ``segment_sum`` histogram pass per
    depth computes the histograms of *all* active nodes simultaneously —
    no per-leaf dynamic gathers (which would defeat XLA). LightGBM's
    ``num_leaves`` cap is honored by ranking candidate splits by gain at each
    level and splitting only as many as the remaining leaf budget allows
    (best-first within a level).
  * Rows are sharded over the ``data`` mesh axis; the histogram reduction is
    the cross-device collective (GSPMD inserts the psum from sharding
    annotations) — this *is* the reference's NetworkManager + socket-ring
    allreduce (``NetworkManager.scala:59-125``), expressed as sharding.
  * Missing values (NaN bin = last bin) route right; thresholds never cover
    the NaN bin.

Histogram channels: (grad, hess, count).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["GrowthConfig", "TreeArrays", "grow_tree", "traverse_binned",
           "predict_raw_forest", "level_cum_tables", "split_gain"]


class GrowthConfig(NamedTuple):
    """Static growth hyper-parameters (one jit cache per distinct config)."""

    max_depth: int
    num_leaves: int
    num_bins: int
    lambda_l1: float
    lambda_l2: float
    learning_rate: float
    min_data_in_leaf: int
    min_sum_hessian: float
    min_gain_to_split: float
    # per-feature monotone constraints (+1/-1/0), () = unconstrained
    # (reference params/LightGBMParams.scala monotoneConstraints; the 'basic'
    # method: split-direction gating + child-value midpoint bounds)
    monotone_constraints: tuple = ()
    # histogram backend: 'segment' (segment_sum -> scatter-add), 'onehot'
    # (row-chunked one-hot matmul — MXU-shaped but XLA materializes the
    # one-hot in HBM), or 'pallas' (fused kernel generating one-hot tiles in
    # VMEM — .pallas_hist). Equivalent results; pick by measurement
    # (benchmarks/gbdt_hist_backends.py)
    hist_impl: str = "segment"
    # categorical features (sorted feature indices; their bins ARE the raw
    # category codes). Split finding is LightGBM's many-vs-many: bins sorted
    # per node by grad/(hess+cat_smooth), prefixes of the sorted order are
    # the candidate left sets — the SAME cumulative-histogram scan as
    # numerical thresholds, just through a per-node permutation (reference
    # params categoricalSlotIndexes, BaseTrainParams.scala)
    categorical_features: tuple = ()
    max_cat_threshold: int = 32
    cat_smooth: float = 10.0


class TreeArrays(NamedTuple):
    """One tree in heap layout; leaf nodes have ``feature == -1``."""

    feature: jax.Array  # (M,) int32, -1 = leaf
    threshold_bin: jax.Array  # (M,) int32, split: bin <= thr goes left
    leaf_value: jax.Array  # (M,) float32
    gain: jax.Array  # (M,) float32, split gain (0 at leaves) — feeds importance
    cover: jax.Array  # (M,) float32, rows reaching the node — feeds TreeSHAP
    # (M, B) uint8 left-membership per bin for categorical splits; (M, 1)
    # zeros when the config has no categorical features. A node is
    # categorical iff its row has any nonzero (valid cat splits always
    # have a nonempty left set)
    cat_mask: jax.Array = None


def max_nodes(max_depth: int) -> int:
    return 2 ** (max_depth + 1) - 1


def _soft_threshold(g: jax.Array, l1: float) -> jax.Array:
    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)


def _leaf_value(g: jax.Array, h: jax.Array, cfg: GrowthConfig) -> jax.Array:
    return -_soft_threshold(g, cfg.lambda_l1) / (h + cfg.lambda_l2 + 1e-12) * cfg.learning_rate


def _split_score(g: jax.Array, h: jax.Array, cfg: GrowthConfig) -> jax.Array:
    gs = _soft_threshold(g, cfg.lambda_l1)
    return gs * gs / (h + cfg.lambda_l2 + 1e-12)


def _level_histogram(bins: jax.Array, g: jax.Array, h: jax.Array, presence: jax.Array,
                     node_of_row: jax.Array, base: int, width: int, num_bins: int,
                     hist_impl: str = "segment") -> jax.Array:
    """(width, F, B, 3) histograms for the ``width`` nodes of one level.

    Scans over features so peak memory stays O(N) regardless of F. Rows whose
    node is outside [base, base+width) (rows resting in already-final leaves)
    are zero-weighted out. Two backends per feature:

    * 'segment': one segment-sum of (N, 3) into (width*B, 3) — lowers to a
      scatter-add, which TPUs serialize;
    * 'onehot': row-chunked one-hot matmul — the same reduction phrased as
      [C, width*B]^T @ [C, 3] MXU matmuls accumulated over chunks (the
      scaling-book recipe for TPU histograms). One-hot 0/1 values are exact
      in any dtype and the dot accumulates in f32, so results match
      'segment' to float rounding.
    """
    valid = (node_of_row >= base) & (node_of_row < base + width)
    rel = jnp.where(valid, node_of_row - base, 0)
    zero = jnp.zeros_like(g)
    data = jnp.stack([jnp.where(valid, g, zero), jnp.where(valid, h, zero),
                      jnp.where(valid, presence, zero)], axis=-1)  # (N, 3)
    WB = width * num_bins

    if hist_impl == "onehot":
        row_chunk = 4096
        n = data.shape[0]
        pad = (-n) % row_chunk
        if pad:
            data = jnp.pad(data, ((0, pad), (0, 0)))  # zero rows: no effect
            rel = jnp.pad(rel, (0, pad))
        data_r = data.reshape(-1, row_chunk, 3)

        def one_feature(carry, f_bins):
            if pad:
                f_bins = jnp.pad(f_bins, (0, pad))
            seg_r = (rel * num_bins + f_bins.astype(jnp.int32)
                     ).reshape(-1, row_chunk)

            def chunk_step(acc, xs):
                seg_c, data_c = xs
                oh = jax.nn.one_hot(seg_c, WB, dtype=data_c.dtype)  # (C, WB)
                return acc + jax.lax.dot_general(
                    oh, data_c, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32), None

            hist, _ = jax.lax.scan(chunk_step,
                                   jnp.zeros((WB, 3), jnp.float32),
                                   (seg_r, data_r))
            return carry, hist.reshape(width, num_bins, 3)
    elif hist_impl == "segment":
        def one_feature(carry, f_bins):
            seg = rel * num_bins + f_bins.astype(jnp.int32)
            hist = jax.ops.segment_sum(data, seg, num_segments=WB)
            return carry, hist.reshape(width, num_bins, 3)
    elif hist_impl == "pallas":
        from .pallas_hist import pallas_segment_histogram

        def one_feature(carry, f_bins):
            seg = rel * num_bins + f_bins.astype(jnp.int32)
            hist = pallas_segment_histogram(seg, data, WB)
            return carry, hist.reshape(width, num_bins, 3)
    else:
        raise ValueError(f"hist_impl must be 'segment', 'onehot' or "
                         f"'pallas', got {hist_impl!r}")

    _, hists = jax.lax.scan(one_feature, 0, jnp.swapaxes(bins, 0, 1))  # (F, W, B, 3)
    return jnp.swapaxes(hists, 0, 1)  # (W, F, B, 3)


def derive_max_depth(max_depth: int, num_leaves: int) -> int:
    """Effective tree depth for a config: the ONE copy of the default-depth
    formula (deep enough for ``num_leaves``, heap-bounded at 12). Serial
    ``train_booster``, the fused sweep, and ``_fused_plan`` grouping all
    call this — a private copy in any of them would let a fused trial train
    a different tree shape than the serial fit of the same config."""
    if max_depth is None or max_depth <= 0:
        max_depth = max(int(np.ceil(np.log2(max(num_leaves, 2)))) + 1, 3)
    return min(max_depth, 12)


def level_cum_tables(hist: jax.Array, num_thresholds: int):
    """Node totals + cumulative left-prefix channels from one level's
    histograms: ``(g_tot, h_tot, c_tot, gl, hl, cl)`` with ``*_tot`` shaped
    (W,) and the left tables (W, F, num_thresholds). Shared by the serial
    per-config step and the fused multi-trial sweep (``gbdt/fused.py``) so
    the two training paths cannot diverge on the prefix-scan math."""
    cum = jnp.cumsum(hist, axis=2)  # (W, F, B, 3)
    total = cum[:, 0, -1, :]  # (W, 3) — feature 0's full sum == node totals
    left = cum[:, :, :num_thresholds, :]  # (W, F, B-1, 3)
    return (total[:, 0], total[:, 1], total[:, 2],
            left[..., 0], left[..., 1], left[..., 2])


def split_gain(g_tot: jax.Array, h_tot: jax.Array, gl: jax.Array,
               hl: jax.Array, cfg) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Candidate split gains (W, F, num_thresholds) plus the right-side
    grad/hess tables. ``cfg`` only needs ``lambda_l1``/``lambda_l2`` — python
    floats on the serial path, traced per-trial scalars on the fused one."""
    gr = g_tot[:, None, None] - gl
    hr = h_tot[:, None, None] - hl
    gain = (_split_score(gl, hl, cfg) + _split_score(gr, hr, cfg)
            - _split_score(g_tot, h_tot, cfg)[:, None, None])
    return gr, hr, gain


def split_ok_mask(cl, cr, hl, hr, cfg):
    """Data-count / hessian-mass split validity (W, F, num_thresholds).
    ``cfg`` needs ``min_data_in_leaf``/``min_sum_hessian`` — python floats
    on the serial path, traced per-trial scalars on the fused one. Shared
    so the two paths cannot diverge on the eligibility rule."""
    return ((cl >= cfg.min_data_in_leaf) & (cr >= cfg.min_data_in_leaf)
            & (hl >= cfg.min_sum_hessian) & (hr >= cfg.min_sum_hessian))


def select_level_splits(gain, c_tot, leaf_count, cfg, width: int,
                        num_thresholds: int):
    """Best split per node + the level's leaf-budget decision: argmax over
    (feature, threshold) — jnp.argmax's first-max tie-break IS part of the
    contract — min_gain gate, and top-(remaining-budget) ranking by gain.
    ``cfg`` needs ``min_gain_to_split``/``num_leaves``. One copy shared by
    the serial level step and the fused sweep; returns
    ``(best_idx, best_gain, best_feat, best_thr, active, do_split)``."""
    flat = gain.reshape(width, -1)
    best_idx = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best_idx[:, None], axis=1)[:, 0]
    best_feat = (best_idx // num_thresholds).astype(jnp.int32)
    best_thr = (best_idx % num_thresholds).astype(jnp.int32)
    # a node is "active" at this level iff it actually holds rows
    active = c_tot > 0
    can_split = active & (best_gain > cfg.min_gain_to_split)
    # leaf budget: each split nets +1 leaf; split the top-(budget) gains
    budget = jnp.maximum(cfg.num_leaves - leaf_count, 0)
    order = jnp.argsort(jnp.where(can_split, -best_gain, jnp.inf))
    rank = jnp.zeros(width, jnp.int32).at[order].set(
        jnp.arange(width, dtype=jnp.int32))
    do_split = can_split & (rank < budget)
    return best_idx, best_gain, best_feat, best_thr, active, do_split


def level_row_partition(bins, node_of_row, do_split, best_feat, best_thr,
                        base: int, width: int):
    """Row→child routing ingredients for one level: which rows sit in a
    splitting node, their winning feature's bin, and the numeric
    left/right decision. Returns ``(rel, row_split, f_of_row, row_bin,
    go_left)`` — callers may override ``go_left`` (categorical membership)
    before applying :func:`route_rows`."""
    here = (node_of_row >= base) & (node_of_row < base + width)
    rel = jnp.where(here, node_of_row - base, 0)
    row_split = do_split[rel] & here
    f_of_row = best_feat[rel]
    row_bin = jnp.take_along_axis(
        bins, f_of_row[:, None].astype(jnp.int32), axis=1)[:, 0]
    go_left = row_bin.astype(jnp.int32) <= best_thr[rel]
    return rel, row_split, f_of_row, row_bin, go_left


def route_rows(node_of_row, row_split, go_left):
    """Move each splitting row to its heap child (left = 2i+1)."""
    child = 2 * node_of_row + jnp.where(go_left, 1, 2)
    return jnp.where(row_split, child, node_of_row)


def _make_level_step(base: int, width: int, cfg: GrowthConfig):
    """One jitted level step: histogram → best splits → budget → update tree +
    row partition. Reused across trees/iterations (same shapes)."""

    B = cfg.num_bins
    num_thresholds = B - 1  # thresholds 0..B-2; the NaN bin is never a left-inclusive cut

    mono = (np.asarray(cfg.monotone_constraints, np.int32)
            if any(cfg.monotone_constraints) else None)

    @jax.jit
    def step(bins, grad, hess, presence, node_of_row, feature, threshold_bin,
             leaf_value, node_gain, node_cover, feat_mask, leaf_count,
             node_lo, node_hi, cat_mask_tree):
        hist = _level_histogram(bins, grad, hess, presence, node_of_row, base,
                                width, B, hist_impl=cfg.hist_impl)
        g_tot, h_tot, c_tot, gl, hl, cl = level_cum_tables(hist,
                                                           num_thresholds)

        cat_order = None
        if cfg.categorical_features:
            F = bins.shape[1]
            cat_idx = np.asarray(cfg.categorical_features, np.int32)
            is_cat_f = np.zeros(F, bool)
            is_cat_f[cat_idx] = True
            # candidate left sets = prefixes of bins sorted by g/(h+smooth);
            # zero-count bins and the NaN bin (last) are never members, so
            # unseen/missing categories route right at predict time. Only
            # the CATEGORICAL columns pay the argsort/cumsum (static gather
            # + scatter-back keeps numerical columns untouched).
            hist_c = hist[:, cat_idx]  # (W, Fc, B, 3)
            gb, hb, cb = hist_c[..., 0], hist_c[..., 1], hist_c[..., 2]
            eligible = (cb > 0) & (jnp.arange(B) != B - 1)[None, None, :]
            ratio = jnp.where(eligible, gb / (hb + cfg.cat_smooth), jnp.inf)
            cat_order = jnp.argsort(ratio, axis=2)  # (W, Fc, B)
            sg = jnp.take_along_axis(jnp.where(eligible, gb, 0.0), cat_order, 2)
            sh = jnp.take_along_axis(jnp.where(eligible, hb, 0.0), cat_order, 2)
            sc = jnp.take_along_axis(jnp.where(eligible, cb, 0.0), cat_order, 2)
            s_ok = jnp.take_along_axis(eligible, cat_order, 2)
            gl = gl.at[:, cat_idx].set(jnp.cumsum(sg, axis=2)[:, :, :num_thresholds])
            hl = hl.at[:, cat_idx].set(jnp.cumsum(sh, axis=2)[:, :, :num_thresholds])
            cl = cl.at[:, cat_idx].set(jnp.cumsum(sc, axis=2)[:, :, :num_thresholds])
            # prefix k (index k-1) valid iff its last bin is eligible and the
            # left set stays within max_cat_threshold categories
            valid_k = (s_ok[:, :, :num_thresholds]
                       & (jnp.arange(num_thresholds) < cfg.max_cat_threshold
                          )[None, None, :])
            # position of each cat feature within cat_idx (for the winning
            # node's order lookup below)
            cat_pos = np.zeros(F, np.int32)
            cat_pos[cat_idx] = np.arange(len(cat_idx), dtype=np.int32)

        gr, hr, gain = split_gain(g_tot, h_tot, gl, hl, cfg)
        cr = c_tot[:, None, None] - cl
        ok = split_ok_mask(cl, cr, hl, hr, cfg) & feat_mask[None, :, None]
        if cfg.categorical_features:
            ok = ok.at[:, cat_idx].set(ok[:, cat_idx] & valid_k)
        if mono is not None:
            # monotone gating: a split on a constrained feature is only valid
            # if the would-be child values respect the direction
            vl = _leaf_value(gl, hl, cfg)
            vr = _leaf_value(gr, hr, cfg)
            c = jnp.asarray(mono)[None, :, None]
            ok &= jnp.where(c > 0, vl <= vr, jnp.where(c < 0, vl >= vr, True))
        gain = jnp.where(ok, gain, -jnp.inf)

        (best_idx, best_gain, best_feat, best_thr, active,
         do_split) = select_level_splits(gain, c_tot, leaf_count, cfg,
                                         width, num_thresholds)

        node_ids = base + jnp.arange(width, dtype=jnp.int32)
        feature = feature.at[node_ids].set(jnp.where(do_split, best_feat, -1))
        threshold_bin = threshold_bin.at[node_ids].set(jnp.where(do_split, best_thr, 0))

        member = None
        if cfg.categorical_features:
            # materialize the winning left set: bins whose rank in the
            # node's sorted order falls inside the chosen prefix
            best_cat_pos = jnp.asarray(cat_pos)[best_feat]
            best_order = jnp.take_along_axis(
                cat_order, best_cat_pos[:, None, None], axis=1)[:, 0]  # (W, B)
            inv_rank = jnp.argsort(best_order, axis=-1)  # inverse permutation
            is_cat_best = jnp.asarray(is_cat_f)[best_feat]
            member = ((inv_rank <= best_thr[:, None])
                      & (is_cat_best & do_split)[:, None])  # (W, B)
            cat_mask_tree = cat_mask_tree.at[node_ids].set(
                member.astype(jnp.uint8))
        lo = node_lo[node_ids]
        hi = node_hi[node_ids]
        # active nodes that do not split become final leaves now (clamped to
        # the monotone bounds inherited from ancestors)
        value = jnp.clip(_leaf_value(g_tot, h_tot, cfg), lo, hi)
        leaf_value = leaf_value.at[node_ids].set(jnp.where(active & ~do_split, value, 0.0))
        node_gain = node_gain.at[node_ids].set(jnp.where(do_split, best_gain, 0.0))
        node_cover = node_cover.at[node_ids].set(c_tot)
        leaf_count = leaf_count + jnp.sum(do_split.astype(jnp.int32))

        # propagate monotone bounds to children: on a +1 split the left
        # subtree is capped at the midpoint and the right floored (basic
        # method); unconstrained splits inherit the parent bounds
        left_ids = 2 * node_ids + 1
        right_ids = 2 * node_ids + 2
        if mono is not None:
            bvl = jnp.take_along_axis(
                _leaf_value(gl, hl, cfg).reshape(width, -1), best_idx[:, None], 1)[:, 0]
            bvr = jnp.take_along_axis(
                _leaf_value(gr, hr, cfg).reshape(width, -1), best_idx[:, None], 1)[:, 0]
            mid = jnp.clip((bvl + bvr) * 0.5, lo, hi)
            cf = jnp.asarray(mono)[best_feat]
            l_hi = jnp.where(do_split & (cf > 0), jnp.minimum(hi, mid), hi)
            r_lo = jnp.where(do_split & (cf > 0), jnp.maximum(lo, mid), lo)
            l_lo = jnp.where(do_split & (cf < 0), jnp.maximum(lo, mid), lo)
            r_hi = jnp.where(do_split & (cf < 0), jnp.minimum(hi, mid), hi)
        else:
            l_lo, l_hi, r_lo, r_hi = lo, hi, lo, hi
        node_lo = node_lo.at[left_ids].set(l_lo)
        node_hi = node_hi.at[left_ids].set(l_hi)
        node_lo = node_lo.at[right_ids].set(r_lo)
        node_hi = node_hi.at[right_ids].set(r_hi)

        # partition rows of split nodes to children
        rel, row_split, f_of_row, row_bin, go_left = level_row_partition(
            bins, node_of_row, do_split, best_feat, best_thr, base, width)
        if cfg.categorical_features:
            in_set = jnp.take_along_axis(
                member[rel], row_bin[:, None].astype(jnp.int32), axis=1)[:, 0]
            go_left = jnp.where(jnp.asarray(is_cat_f)[f_of_row], in_set,
                                go_left)
        node_of_row = route_rows(node_of_row, row_split, go_left)
        return (node_of_row, feature, threshold_bin, leaf_value, node_gain,
                node_cover, leaf_count, node_lo, node_hi, cat_mask_tree)

    return step


def _make_final_level(base: int, width: int, cfg: GrowthConfig):
    """At max depth every active node becomes a leaf (no histogram needed —
    just per-node g/h totals)."""

    @jax.jit
    def step(grad, hess, presence, node_of_row, leaf_value, node_cover,
             node_lo, node_hi):
        valid = (node_of_row >= base) & (node_of_row < base + width)
        rel = jnp.where(valid, node_of_row - base, 0)
        zero = jnp.zeros_like(grad)
        data = jnp.stack([jnp.where(valid, grad, zero), jnp.where(valid, hess, zero),
                          jnp.where(valid, presence, zero)], axis=-1)
        tot = jax.ops.segment_sum(data, rel, num_segments=width)  # (W, 3)
        active = tot[:, 2] > 0
        node_ids = base + jnp.arange(width, dtype=jnp.int32)
        value = jnp.clip(_leaf_value(tot[:, 0], tot[:, 1], cfg),
                         node_lo[node_ids], node_hi[node_ids])
        return (leaf_value.at[node_ids].set(jnp.where(active, value, 0.0)),
                node_cover.at[node_ids].set(tot[:, 2]))

    return step


@functools.lru_cache(maxsize=None)
def _level_steps(cfg: GrowthConfig):
    steps = [_make_level_step(2**d - 1, 2**d, cfg) for d in range(cfg.max_depth)]
    final = _make_final_level(2**cfg.max_depth - 1, 2**cfg.max_depth, cfg)
    return steps, final


def grow_tree(bins: jax.Array, grad: jax.Array, hess: jax.Array, presence: jax.Array,
              cfg: GrowthConfig, feat_mask: jax.Array) -> TreeArrays:
    """Grow one tree. ``bins`` (N, F) int; ``grad``/``hess`` (N,) float32
    (sample weights / bagging already folded in); ``presence`` (N,) float32
    0/1 marks real vs padded/bagged-out rows (drives the count channel);
    ``feat_mask`` (F,) bool."""
    m = max_nodes(cfg.max_depth)
    feature = jnp.full(m, -1, jnp.int32)
    threshold_bin = jnp.zeros(m, jnp.int32)
    leaf_value = jnp.zeros(m, jnp.float32)
    node_gain = jnp.zeros(m, jnp.float32)
    node_cover = jnp.zeros(m, jnp.float32)
    node_lo = jnp.full(m, -jnp.inf, jnp.float32)
    node_hi = jnp.full(m, jnp.inf, jnp.float32)
    node_of_row = jnp.zeros(bins.shape[0], jnp.int32)
    leaf_count = jnp.asarray(1, jnp.int32)
    cat_width = cfg.num_bins if cfg.categorical_features else 1
    cat_mask = jnp.zeros((m, cat_width), jnp.uint8)

    steps, final = _level_steps(cfg)
    for step in steps:
        (node_of_row, feature, threshold_bin, leaf_value, node_gain, node_cover,
         leaf_count, node_lo, node_hi, cat_mask) = step(
            bins, grad, hess, presence, node_of_row, feature, threshold_bin,
            leaf_value, node_gain, node_cover, feat_mask, leaf_count,
            node_lo, node_hi, cat_mask)
    leaf_value, node_cover = final(grad, hess, presence, node_of_row,
                                   leaf_value, node_cover, node_lo, node_hi)
    return TreeArrays(feature, threshold_bin, leaf_value, node_gain, node_cover,
                      cat_mask)


@functools.partial(jax.jit, static_argnums=(2,))
def traverse_binned(bins: jax.Array, tree: TreeArrays, max_depth: int) -> jax.Array:
    """Leaf values for binned rows (used to update train scores incrementally).
    A node routes categorically iff its cat_mask row is nonempty (valid
    categorical splits always have a nonempty left set)."""
    has_cat = tree.cat_mask is not None and tree.cat_mask.shape[1] > 1

    def body(_, node):
        f = tree.feature[node]
        b = jnp.take_along_axis(bins, jnp.maximum(f, 0)[:, None].astype(jnp.int32), axis=1)[:, 0]
        go_left = b.astype(jnp.int32) <= tree.threshold_bin[node]
        if has_cat:
            mask_row = tree.cat_mask[node]  # (N, B)
            is_cat = mask_row.sum(axis=1) > 0
            in_set = jnp.take_along_axis(
                mask_row, b[:, None].astype(jnp.int32), axis=1)[:, 0] > 0
            go_left = jnp.where(is_cat, in_set, go_left)
        child = 2 * node + jnp.where(go_left, 1, 2)
        return jnp.where(f < 0, node, child)

    node = jax.lax.fori_loop(0, max_depth, body,
                             jnp.zeros(bins.shape[0], jnp.int32))
    return tree.leaf_value[node]


def cat_route_left(fv: jax.Array, go_left: jax.Array,
                   mask_node: jax.Array | None) -> jax.Array:
    """Overlay categorical routing on a numerical go-left decision: nodes
    whose mask row is nonempty route by left-set membership of the raw
    category code; NaN / out-of-range / non-members route right. THE single
    routing rule — shared by raw prediction, leaf indexing, and the
    imported-model walker so they cannot diverge."""
    if mask_node is None:
        return go_left
    B = mask_node.shape[-1]
    is_cat = mask_node.sum(axis=-1) > 0
    idx = jnp.clip(fv.astype(jnp.int32), 0, B - 1)
    in_set = (jnp.take_along_axis(mask_node, idx[:, None], axis=1)[:, 0] > 0) \
        & (fv >= 0) & (fv < B)
    return jnp.where(is_cat, in_set, go_left)


def predict_raw_forest(x: jax.Array, feature: jax.Array, threshold_value: jax.Array,
                       leaf_value: jax.Array, max_depth: int,
                       cat_masks: jax.Array | None = None) -> jax.Array:
    """Raw-feature forest prediction (standalone model, no BinMapper needed).

    ``feature``/``threshold_value``/``leaf_value``: (T, M) stacked trees;
    ``cat_masks``: optional (T, M, B) uint8 — for categorical nodes the raw
    value IS the category code, membership routes left. Returns per-tree
    leaf sums (N,). NaN/out-of-range features route right (comparisons with
    NaN are False; non-members route right), matching training's
    NaN-bin-goes-right rule.
    """

    def _go_left(fv, thr_node, mask_node):
        return cat_route_left(fv, fv <= thr_node, mask_node)

    def one_tree(carry, tree):
        feat, thr, val, cm = tree

        def body(_, node):
            f = feat[node]
            fv = jnp.take_along_axis(x, jnp.maximum(f, 0)[:, None].astype(jnp.int32), axis=1)[:, 0]
            go_left = _go_left(fv, thr[node], None if cm is None else cm[node])
            child = 2 * node + jnp.where(go_left, 1, 2)
            return jnp.where(f < 0, node, child)

        node = jax.lax.fori_loop(0, max_depth, body, jnp.zeros(x.shape[0], jnp.int32))
        return carry + val[node], None

    out, _ = jax.lax.scan(one_tree, jnp.zeros(x.shape[0], jnp.float32),
                          (feature, threshold_value, leaf_value, cat_masks))
    return out


def leaf_index_forest(x: jax.Array, feature: jax.Array, threshold_value: jax.Array,
                      max_depth: int,
                      cat_masks: jax.Array | None = None) -> jax.Array:
    """Per-tree leaf index for each row, shape (N, T) — the reference's
    ``predictLeaf`` output (``LightGBMBooster.scala:394`` area)."""

    def one_tree(carry, tree):
        feat, thr, cm = tree

        def body(_, node):
            f = feat[node]
            fv = jnp.take_along_axis(x, jnp.maximum(f, 0)[:, None].astype(jnp.int32), axis=1)[:, 0]
            go_left = cat_route_left(fv, fv <= thr[node],
                                     None if cm is None else cm[node])
            child = 2 * node + jnp.where(go_left, 1, 2)
            return jnp.where(f < 0, node, child)

        node = jax.lax.fori_loop(0, max_depth, body, jnp.zeros(x.shape[0], jnp.int32))
        return carry, node

    _, nodes = jax.lax.scan(one_tree, 0, (feature, threshold_value, cat_masks))
    return jnp.swapaxes(nodes, 0, 1)
