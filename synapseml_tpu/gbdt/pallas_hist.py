"""Pallas TPU histogram kernel — the GBDT hot loop's third backend.

Reference analog: the CUDA/C++ histogram construction inside
``LGBM_BoosterUpdateOneIter`` (``booster/LightGBMBooster.scala:355``). The
XLA backends in :mod:`.trees` both have a structural weakness on TPU:

* ``segment`` lowers to a scatter-add, which the TPU serializes row by row;
* ``onehot`` phrases the reduction as one-hot matmuls, but XLA materializes
  the ``[chunk, width*bins]`` one-hot operand in HBM every chunk — the
  histogram becomes HBM-bandwidth-bound on a matrix of zeros.

This kernel keeps the one-hot trick but generates each tile ON THE FLY in
VMEM (an iota-compare against the segment ids) and feeds the MXU directly:
HBM traffic is one stream over (seg, grad, hess, count) per feature, nothing
else. Grid = (bin-tiles, row-chunks) with chunks innermost, so each output
tile stays VMEM-resident while every chunk accumulates into it.

Interpret mode makes the same kernel run (slowly) on CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["pallas_segment_histogram"]

_ROW_CHUNK = 1024     # rows per grid step (seg/g/h/c stream tile)
_BIN_TILE = 512       # histogram slots per output tile (lanes)


def _hist_kernel(seg_ref, g_ref, h_ref, c_ref, out_ref, *, bin_tile: int,
                 chunk: int):
    """One (bin-tile j, row-chunk c) program: out[j] += onehot(seg_c)^T @ data.

    seg/g/h/c blocks: [1, chunk]; out block: [bin_tile, 3] (revisited across
    the chunk dimension — accumulate, init at the first chunk).
    """
    from jax.experimental import pallas as pl

    j = pl.program_id(0)
    c = pl.program_id(1)

    @pl.when(c == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    seg = seg_ref[...]                                   # [1, chunk] int32
    # one-hot tile generated in VMEM: bins_col[b, r] = j*bin_tile + b
    bins_col = j * bin_tile + jax.lax.broadcasted_iota(
        jnp.int32, (bin_tile, chunk), 0)
    oh = (seg == bins_col).astype(jnp.float32)           # [bin_tile, chunk]
    data = jnp.concatenate([g_ref[...], h_ref[...], c_ref[...]], axis=0)
    # [bin_tile, chunk] @ [3, chunk]^T on the MXU, f32 accumulation
    out_ref[...] += jax.lax.dot_general(
        oh, data, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnums=(2,))
def pallas_segment_histogram(seg: jax.Array, data: jax.Array,
                             num_segments: int) -> jax.Array:
    """``segment_sum(data, seg, num_segments)`` as a Pallas TPU kernel.

    seg: (N,) int32 in [0, num_segments) — out-of-range ids contribute
    nowhere (the padding convention). data: (N, 3) f32 (grad, hess, count).
    Returns (num_segments, 3) f32.
    """
    from jax.experimental import pallas as pl

    if jax.default_backend() not in ("tpu", "cpu"):
        import warnings

        warnings.warn(
            "histogram_impl='pallas' has a compiled kernel only on TPU; on "
            f"{jax.default_backend()!r} it runs in interpret mode, orders of "
            "magnitude slower — use 'segment' or 'onehot' here",
            stacklevel=2)
    N = seg.shape[0]
    # floor of 128: last-dim tiles below the TPU's 128-lane register width
    # are not guaranteed to compile in Mosaic (padding covers the unused tail)
    chunk = min(_ROW_CHUNK, max(int(2 ** np.ceil(np.log2(max(N, 8)))), 128))
    n_chunks = -(-N // chunk)
    n_pad = n_chunks * chunk - N
    bin_tile = min(_BIN_TILE, max(-(-num_segments // 128) * 128, 128))
    n_tiles = -(-num_segments // bin_tile)
    wb_pad = n_tiles * bin_tile

    # padded rows get seg = wb_pad: matches no bin tile, contributes nothing
    seg_p = jnp.pad(seg.astype(jnp.int32), (0, n_pad),
                    constant_values=wb_pad).reshape(n_chunks, chunk)
    gp, hp, cp = (jnp.pad(data[:, i], (0, n_pad)).reshape(n_chunks, chunk)
                  for i in range(3))

    out = pl.pallas_call(
        functools.partial(_hist_kernel, bin_tile=bin_tile, chunk=chunk),
        grid=(n_tiles, n_chunks),
        in_specs=[pl.BlockSpec((1, chunk), lambda j, c: (c, 0))] * 4,
        out_specs=pl.BlockSpec((bin_tile, 3), lambda j, c: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((wb_pad, 3), jnp.float32),
        interpret=jax.default_backend() != "tpu",
    )(seg_p, gp, hp, cp)
    return out[:num_segments]
