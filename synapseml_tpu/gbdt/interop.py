"""LightGBM model-string interop: emit and parse LightGBM ``model.txt``.

Reference: ``booster/LightGBMBooster.scala`` round-trips the native model
string (``saveNativeModel:458`` / the ``modelString`` param that warm-starts
training and rehydrates models). Here:

  * :func:`to_lightgbm_string` — serialize a :class:`TpuBooster` in LightGBM's
    text format (child-array trees, ``Tree=N`` blocks), so models trained on
    TPU load into stock LightGBM tooling;
  * :func:`parse_lightgbm_string` / :class:`ImportedBooster` — load a model
    produced by real LightGBM (arbitrary tree shapes, not just our heap
    layout) and serve it through the same jitted predict path, so existing
    LightGBM models migrate in.

LightGBM node encoding recap: per tree, arrays index INTERNAL nodes
(``num_leaves - 1`` of them); ``left_child``/``right_child`` entries >= 0 are
internal node ids, negative entries are leaves encoded as ``~leaf_idx``
(= ``-leaf-1``). ``decision_type`` bit 1 = categorical (the node's
``threshold`` is then an ordinal into ``cat_boundaries``, and
``cat_threshold`` holds 32-bit bitset words of member categories — members
route left, NaN/out-of-range/non-members right), bit 2 = default-left
(missing values go left).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["to_lightgbm_string", "parse_lightgbm_string", "ImportedBooster"]

_CAT_MASK = 1
_DEFAULT_LEFT_MASK = 2
# decision_type bits 2-3: missing_type (0=None, 1=Zero, 2=NaN)
_MISSING_NONE, _MISSING_ZERO, _MISSING_NAN = 0, 1, 2
_ZERO_THRESHOLD = 1e-35


# ---------------------------------------------------------------------------
# export: heap trees -> LightGBM child arrays
# ---------------------------------------------------------------------------

def _mask_to_words(mask: np.ndarray) -> list[int]:
    """Bin-membership mask -> LightGBM 32-bit bitset words."""
    cats = np.nonzero(mask)[0]
    n_words = int(cats.max()) // 32 + 1 if cats.size else 1
    words = [0] * n_words
    for c in cats:
        words[int(c) // 32] |= 1 << (int(c) % 32)
    return words


def _heap_to_children(feature: np.ndarray, threshold: np.ndarray,
                      leaf_value: np.ndarray, gain: np.ndarray,
                      cat_mask: np.ndarray | None = None):
    """One heap tree -> (split_feature, split_gain, threshold, left, right,
    leaf_values, decision_type, cat_boundaries, cat_threshold) in LightGBM
    encoding. Categorical nodes (nonempty cat_mask row) get decision_type
    bit 1 and a threshold that is their ordinal into cat_boundaries."""
    internal: list[int] = []          # heap idx of internal nodes, BFS order
    leaves: list[int] = []            # heap idx of leaf nodes, BFS order
    index_of: dict[int, int] = {}

    order = [0]
    while order:
        h = order.pop(0)
        if feature[h] >= 0:
            index_of[h] = len(internal)
            internal.append(h)
            order.append(2 * h + 1)
            order.append(2 * h + 2)
        else:
            index_of[h] = ~len(leaves)
            leaves.append(h)
    if not internal:  # single-leaf tree
        return ([], [], [], [], [], [float(leaf_value[0])], [], [0], [])

    left = [index_of[2 * h + 1] for h in internal]
    right = [index_of[2 * h + 2] for h in internal]
    thr_out, dt_out = [], []
    cat_boundaries, cat_words = [0], []
    for h in internal:
        is_cat = cat_mask is not None and bool(cat_mask[h].any())
        if is_cat:
            words = _mask_to_words(cat_mask[h])
            thr_out.append(float(len(cat_boundaries) - 1))  # ordinal
            # keep missing_type=NaN bits alongside the categorical bit so
            # stock LightGBM treats NaN as missing (-> right), matching our
            # routing, instead of coercing it to category 0
            dt_out.append(_CAT_MASK | (_MISSING_NAN << 2))
            cat_words.extend(words)
            cat_boundaries.append(len(cat_words))
        else:
            thr_out.append(float(threshold[h]))
            # NaN routes right: missing_type=NaN (bits 2-3 = 2), default_left=0
            dt_out.append(_MISSING_NAN << 2)
    return ([int(feature[h]) for h in internal],
            [float(gain[h]) for h in internal],
            thr_out, left, right,
            [float(leaf_value[h]) for h in leaves],
            dt_out, cat_boundaries, cat_words)


def to_lightgbm_string(booster) -> str:
    """Serialize a TpuBooster (heap trees) or ImportedBooster (child arrays)
    as a LightGBM model.txt string.

    ``init_score`` is folded into each class's FIRST tree (LightGBM's
    boost_from_average bakes the prior into leaf values the same way)."""
    if isinstance(booster, ImportedBooster):
        return _imported_to_string(booster)
    K = booster.num_model_out
    T = booster.best_iteration or booster.num_iterations
    # LightGBM objective strings; link-carrying regressions pass through by
    # name so the round-trip (and stock LightGBM) keep the link function
    obj = {"binary": "binary sigmoid:1",
           "multiclass": f"multiclass num_class:{K}",
           "lambdarank": "lambdarank"}.get(booster.objective, booster.objective)
    out = [
        "tree", "version=v3",
        f"num_class={K if booster.objective == 'multiclass' else 1}",
        f"num_tree_per_iteration={K}",
        "label_index=0",
        f"max_feature_idx={booster.num_features - 1}",
        f"objective={obj}",
        "feature_names=" + " ".join(f"Column_{i}" for i in range(booster.num_features)),
        "feature_infos=" + " ".join(["[-inf:inf]"] * booster.num_features),
    ]
    if getattr(booster, "average_output", False):
        # stock LightGBM writes this flag BARE and reads it by key presence
        out.append("average_output")
    out.append("")
    for t in range(T):
        for k in range(K):
            cm = (None if getattr(booster, "cat_mask", None) is None
                  else booster.cat_mask[t, k])
            (feat, gain, thr, left, right, leaf_vals, dt, cat_b,
             cat_w) = _heap_to_children(
                booster.feature[t, k], booster.threshold_value[t, k],
                booster.leaf_value[t, k], booster.gain[t, k], cat_mask=cm)
            if t == 0:
                adj = float(booster.init_score[k])
                if getattr(booster, "average_output", False):
                    # rf predict divides the tree sum by T before adding init;
                    # folding init*T keeps (init*T + sum)/T == init + sum/T
                    adj *= T
                leaf_vals = [v + adj for v in leaf_vals]
            n_leaves = len(leaf_vals)
            n_cat = len(cat_b) - 1 if cat_w else 0
            blk = [f"Tree={t * K + k}", f"num_leaves={n_leaves}",
                   f"num_cat={n_cat}"]
            if feat:
                blk += [
                    "split_feature=" + " ".join(map(str, feat)),
                    "split_gain=" + " ".join(f"{g:.17g}" for g in gain),
                    "threshold=" + " ".join(f"{v:.17g}" for v in thr),
                    "decision_type=" + " ".join(map(str, dt)),
                    "left_child=" + " ".join(map(str, left)),
                    "right_child=" + " ".join(map(str, right)),
                ]
                if n_cat:
                    blk += ["cat_boundaries=" + " ".join(map(str, cat_b)),
                            "cat_threshold=" + " ".join(map(str, cat_w))]
            blk += ["leaf_value=" + " ".join(f"{v:.17g}" for v in leaf_vals),
                    "shrinkage=1", ""]
            out += blk
    out += ["end of trees", "", "parameters:", "end of parameters", ""]
    return "\n".join(out)


# ---------------------------------------------------------------------------
# import: LightGBM model.txt -> jitted predictor
# ---------------------------------------------------------------------------

@dataclass
class _Tree:
    split_feature: np.ndarray
    threshold: np.ndarray
    left: np.ndarray
    right: np.ndarray
    leaf_value: np.ndarray
    default_left: np.ndarray
    missing_type: np.ndarray
    # (n_internal, B) uint8 member-category mask; all-zero rows = numerical
    cat_member: np.ndarray | None = None


@dataclass
class ImportedBooster:
    """A LightGBM-format forest served by a jitted child-array walker.
    API-compatible with TpuBooster's scoring surface so it slots into the
    LightGBM*Model transformers (``booster=`` param)."""

    trees: list[_Tree]
    num_model_out: int
    objective: str
    num_features: int
    average_output: bool = False
    init_score: np.ndarray = field(default_factory=lambda: np.zeros(1, np.float32))
    best_iteration: int | None = None

    @property
    def num_iterations(self) -> int:
        return len(self.trees) // max(self.num_model_out, 1)

    def _packed(self):
        """Pad per-tree arrays to a common internal-node count and stack."""
        if getattr(self, "_packed_cache", None) is None:
            m = max(max(len(t.split_feature), 1) for t in self.trees)
            L = max(max(len(t.leaf_value), 1) for t in self.trees)

            def pad(a, n, fill):
                a = np.asarray(a)
                return np.concatenate([a, np.full(n - len(a), fill, a.dtype)]) \
                    if len(a) < n else a

            packed = tuple(
                np.stack([pad(getattr(t, name), m if name != "leaf_value" else L,
                              fill) for t in self.trees])
                for name, fill in (("split_feature", 0), ("threshold", 0.0),
                                   ("left", -1), ("right", -1),
                                   ("leaf_value", 0.0), ("default_left", 0),
                                   ("missing_type", 0)))
            B = max((t.cat_member.shape[1] for t in self.trees
                     if t.cat_member is not None), default=0)
            cmem = None
            if B:
                cmem = np.zeros((len(self.trees), m, B), np.uint8)
                for i, t in enumerate(self.trees):
                    if t.cat_member is not None and t.cat_member.size:
                        ni, bi = t.cat_member.shape
                        cmem[i, :ni, :bi] = t.cat_member
            self._packed_cache = packed + (cmem,)
        return self._packed_cache

    def raw_score(self, features: np.ndarray,
                  num_iterations: int | None = None) -> np.ndarray:
        feat, thr, left, right, leafv, dleft, mtype, cmem = self._packed()
        K = self.num_model_out
        n_it = num_iterations or self.best_iteration or self.num_iterations
        n_it = min(n_it, self.num_iterations)
        x = jnp.asarray(np.asarray(features, np.float32))
        total = _walk_forest(x, jnp.asarray(feat), jnp.asarray(thr, jnp.float32),
                             jnp.asarray(left), jnp.asarray(right),
                             jnp.asarray(leafv, jnp.float32),
                             jnp.asarray(dleft), jnp.asarray(mtype),
                             None if cmem is None else jnp.asarray(cmem),
                             K, n_it,
                             int(np.ceil(np.log2(leafv.shape[1] + 1))) + 2)
        out = np.asarray(total)
        if self.average_output:
            out = out / n_it
        return out + np.asarray(self.init_score)[None, :]

    def predict(self, features: np.ndarray,
                num_iterations: int | None = None) -> np.ndarray:
        from . import objectives as obj

        s = self.raw_score(features, num_iterations)
        try:
            o = obj.get_objective(self.objective,
                                  num_class=max(self.num_model_out, 2))
        except (KeyError, ValueError):
            o = obj.get_objective("regression", num_class=2)
        return np.asarray(o.transform(jnp.asarray(s)))


@functools.partial(jax.jit, static_argnums=(9, 10, 11))
def _walk_forest(x, feat, thr, left, right, leafv, dleft, mtype, cmem, K: int,
                 n_it: int, max_depth: int):
    """Sum leaf values over trees [0, n_it*K), per class K. Node state is the
    LightGBM encoding itself: >=0 internal, negative = settled leaf."""
    N = x.shape[0]

    def one_tree(t_idx):
        tf, tt = feat[t_idx], thr[t_idx]
        tl, tr, dv, mt = left[t_idx], right[t_idx], dleft[t_idx], mtype[t_idx]
        cm = None if cmem is None else cmem[t_idx]

        def body(_, node):
            live = node >= 0
            idx = jnp.maximum(node, 0)
            f = tf[idx]
            v = jnp.take_along_axis(x, f[:, None].astype(jnp.int32), axis=1)[:, 0]
            m = mt[idx]
            # missing_type semantics: Zero -> |v|<=1e-35 or NaN is missing;
            # NaN -> NaN is missing; None -> NaN still falls to the default
            is_nan = jnp.isnan(v)
            is_missing = jnp.where(m == _MISSING_ZERO,
                                   is_nan | (jnp.abs(v) <= _ZERO_THRESHOLD),
                                   is_nan)
            go_left = jnp.where(is_missing, dv[idx] > 0, v <= tt[idx])
            if cm is not None:
                from .trees import cat_route_left

                go_left = cat_route_left(v, go_left, cm[idx])
            nxt = jnp.where(go_left, tl[idx], tr[idx])
            return jnp.where(live, nxt, node)

        node = jax.lax.fori_loop(0, max_depth + leafv.shape[1], body,
                                 jnp.zeros(N, jnp.int32))
        leaf_idx = jnp.maximum(~node, 0)  # ~leaf encoding; live nodes can't remain
        return leafv[t_idx, leaf_idx]

    def per_class(k):
        def add_iter(t, acc):
            return acc + one_tree(t * K + k)

        return jax.lax.fori_loop(0, n_it, add_iter, jnp.zeros(N, jnp.float32))

    return jnp.stack([per_class(k) for k in range(K)], axis=1)


def _parse_block(lines: list[str]) -> dict:
    out = {}
    for ln in lines:
        if "=" in ln:
            k, v = ln.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def parse_lightgbm_string(text: str) -> ImportedBooster:
    """Parse a LightGBM model.txt (stock LightGBM or our export)."""
    header_lines: list[str] = []
    bare_flags: set[str] = set()
    tree_blocks: list[list[str]] = []
    cur: list[str] | None = None
    for ln in text.splitlines():
        s = ln.strip()
        if s.startswith("Tree="):
            cur = [s]
            tree_blocks.append(cur)
        elif s == "end of trees":
            cur = None
        elif cur is not None:
            cur.append(s)
        elif s:
            if "=" not in s:
                bare_flags.add(s)  # stock writes e.g. 'average_output' bare
            header_lines.append(s)
    head = _parse_block(header_lines)
    objective = head.get("objective", "regression")
    num_tpi = int(head.get("num_tree_per_iteration", 1))
    num_features = int(head.get("max_feature_idx", 0)) + 1

    trees: list[_Tree] = []
    for blk in tree_blocks:
        d = _parse_block(blk)
        n_leaves = int(d.get("num_leaves", 1))
        if "split_feature" in d and n_leaves > 1:
            dt = [int(t) for t in d["decision_type"].split()]
            thresholds = np.asarray(d["threshold"].split(), np.float64)
            cat_member = None
            n_cat = int(d.get("num_cat", 0))
            if n_cat > 0:
                bounds = [int(v) for v in d["cat_boundaries"].split()]
                words = [int(v) for v in d["cat_threshold"].split()]
                max_words = max(bounds[i + 1] - bounds[i] for i in range(n_cat))
                B = 32 * max_words
                cat_member = np.zeros((len(dt), B), np.uint8)
                for j, t_dt in enumerate(dt):
                    if t_dt & _CAT_MASK:
                        o = int(thresholds[j])
                        for wi, w in enumerate(words[bounds[o]:bounds[o + 1]]):
                            w &= 0xFFFFFFFF
                            for b in range(32):
                                if (w >> b) & 1:
                                    cat_member[j, wi * 32 + b] = 1
            trees.append(_Tree(
                split_feature=np.asarray(d["split_feature"].split(), np.int32),
                threshold=thresholds,
                left=np.asarray(d["left_child"].split(), np.int32),
                right=np.asarray(d["right_child"].split(), np.int32),
                leaf_value=np.asarray(d["leaf_value"].split(), np.float64),
                default_left=np.asarray(
                    [(t & _DEFAULT_LEFT_MASK) > 0 for t in dt], np.int32),
                missing_type=np.asarray([(t >> 2) & 3 for t in dt], np.int32),
                cat_member=cat_member))
        else:
            trees.append(_Tree(
                split_feature=np.zeros(0, np.int32),
                threshold=np.zeros(0, np.float64),
                left=np.zeros(0, np.int32), right=np.zeros(0, np.int32),
                leaf_value=np.asarray(d["leaf_value"].split(), np.float64),
                default_left=np.zeros(0, np.int32),
                missing_type=np.zeros(0, np.int32)))

    first = objective.split()[0] if objective else "regression"
    if first == "multiclass":
        K, base = num_tpi, "multiclass"
    elif first == "binary":
        K, base = 1, "binary"
    elif first == "lambdarank":
        K, base = 1, "lambdarank"
    elif first in ("regression_l1", "huber", "poisson", "quantile",
                   "tweedie", "gamma", "mape"):
        K, base = 1, first  # link-carrying regression objectives
    else:
        K, base = 1, "regression"
    avg = (head.get("average_output", "0") == "1"
           or "average_output" in bare_flags)
    return ImportedBooster(trees=trees, num_model_out=K, objective=base,
                           num_features=num_features, average_output=avg,
                           init_score=np.zeros(K, np.float32))


def _imported_to_string(b: "ImportedBooster") -> str:
    """Re-serialize an imported child-array forest (migrate-in models persist
    too — saveNativeModel parity for ImportedBooster-backed transformers)."""
    K = b.num_model_out
    obj = {"binary": "binary sigmoid:1",
           "multiclass": f"multiclass num_class:{K}",
           "lambdarank": "lambdarank"}.get(b.objective, b.objective)
    out = ["tree", "version=v3",
           f"num_class={K if b.objective == 'multiclass' else 1}",
           f"num_tree_per_iteration={K}", "label_index=0",
           f"max_feature_idx={b.num_features - 1}",
           f"objective={obj}",
           "feature_names=" + " ".join(f"Column_{i}" for i in range(b.num_features)),
           "feature_infos=" + " ".join(["[-inf:inf]"] * b.num_features)]
    if b.average_output:
        out.append("average_output")
    out.append("")
    for i, t in enumerate(b.trees):
        cat_b, cat_w, dts, thr_out = [0], [], [], []
        for j in range(len(t.split_feature)):
            is_cat = (t.cat_member is not None and j < len(t.cat_member)
                      and bool(t.cat_member[j].any()))
            if is_cat:
                words = _mask_to_words(t.cat_member[j])
                thr_out.append(float(len(cat_b) - 1))
                dts.append(_CAT_MASK
                           | int(_DEFAULT_LEFT_MASK * bool(t.default_left[j]))
                           | (int(t.missing_type[j]) << 2))
                cat_w.extend(words)
                cat_b.append(len(cat_w))
            else:
                thr_out.append(float(t.threshold[j]))
                dts.append(int(_DEFAULT_LEFT_MASK * bool(t.default_left[j]))
                           | (int(t.missing_type[j]) << 2))
        n_cat = len(cat_b) - 1 if cat_w else 0
        blk = [f"Tree={i}", f"num_leaves={len(t.leaf_value)}",
               f"num_cat={n_cat}"]
        if len(t.split_feature):
            blk += ["split_feature=" + " ".join(map(str, t.split_feature)),
                    "split_gain=" + " ".join(["0"] * len(t.split_feature)),
                    "threshold=" + " ".join(f"{v:.17g}" for v in thr_out),
                    "decision_type=" + " ".join(map(str, dts)),
                    "left_child=" + " ".join(map(str, t.left)),
                    "right_child=" + " ".join(map(str, t.right))]
            if n_cat:
                blk += ["cat_boundaries=" + " ".join(map(str, cat_b)),
                        "cat_threshold=" + " ".join(map(str, cat_w))]
        blk += ["leaf_value=" + " ".join(f"{v:.17g}" for v in t.leaf_value),
                "shrinkage=1", ""]
        out += blk
    out += ["end of trees", "", "parameters:", "end of parameters", ""]
    return "\n".join(out)
