"""TreeSHAP — exact per-feature contributions for the heap-layout forests.

Reference: ``booster/LightGBMBooster.scala:418`` ``featuresShap`` (LightGBM's
``predict_contrib``). This is the polynomial-time Tree SHAP algorithm
(Lundberg et al.) over our fixed-shape heap trees, vectorized over rows with
numpy: path one-fractions and permutation weights are (N,) arrays, so one
recursion over the tree covers the whole row batch. Output layout matches
LightGBM: per model-output ``F`` feature columns plus a bias column (expected
value), and ``sum(contrib, -1) == raw_score`` exactly (additivity).
"""

from __future__ import annotations

import numpy as np

__all__ = ["forest_shap"]


class _Path:
    """One SHAP path: parallel lists of feature idx, zero/one fractions and
    permutation weights; ``o``/``w`` entries are per-row (N,) arrays."""

    __slots__ = ("f", "z", "o", "w")

    def __init__(self, f, z, o, w):
        self.f, self.z, self.o, self.w = f, z, o, w

    def copy(self):
        return _Path(list(self.f), list(self.z), [x.copy() for x in self.o],
                     [x.copy() for x in self.w])


def _extend(m: _Path, pz: float, po: np.ndarray, pi: int) -> None:
    l = len(m.f)
    m.f.append(pi)
    m.z.append(pz)
    m.o.append(po)
    m.w.append(np.ones_like(po) if l == 0 else np.zeros_like(po))
    for i in range(l - 1, -1, -1):
        m.w[i + 1] = m.w[i + 1] + po * m.w[i] * ((i + 1) / (l + 1))
        m.w[i] = pz * m.w[i] * ((l - i) / (l + 1))


def _unwound_sum(m: _Path, i: int) -> np.ndarray:
    """Sum of path weights with element i unwound (without mutating m)."""
    l = len(m.f) - 1
    o, z = m.o[i], m.z[i]
    total = np.zeros_like(m.w[0])
    n = m.w[l].copy()
    o_nonzero = o != 0
    safe_o = np.where(o_nonzero, o, 1.0)
    for j in range(l - 1, -1, -1):
        # where o != 0: invert the extend step; where o == 0: closed form
        t = np.where(o_nonzero,
                     n * (l + 1) / ((j + 1) * safe_o),
                     m.w[j] * (l + 1) / (max(l - j, 1) * z) if z != 0
                     else np.zeros_like(n))
        total = total + t
        n = np.where(o_nonzero, m.w[j] - t * z * ((l - j) / (l + 1)), n)
    return total


def _unwind(m: _Path, i: int) -> _Path:
    """Remove path element i (the inverse of _extend at position i)."""
    l = len(m.f) - 1
    o, z = m.o[i], m.z[i]
    out = m.copy()
    n = out.w[l].copy()
    o_nonzero = o != 0
    safe_o = np.where(o_nonzero, o, 1.0)
    for j in range(l - 1, -1, -1):
        if z != 0:
            t_zero = out.w[j] * (l + 1) / (max(l - j, 1) * z)
        else:
            t_zero = np.zeros_like(n)
        t = np.where(o_nonzero, n * (l + 1) / ((j + 1) * safe_o), t_zero)
        n = np.where(o_nonzero, out.w[j] - t * z * ((l - j) / (l + 1)), n)
        out.w[j] = t
    out.f.pop(i)
    out.z.pop(i)
    out.o.pop(i)
    out.w.pop()  # weights were recomputed in place for the shortened path
    return out


def _tree_shap(feature, threshold, value, cover, X, phi, cat_mask=None):
    """Accumulate one tree's contributions into phi (N, F+1); ``cat_mask``
    (M, B) uint8 routes categorical nodes by left-set membership."""
    N = X.shape[0]

    def recurse(node: int, m: _Path, pz: float, po: np.ndarray, pi: int):
        m = m.copy()
        # duplicate feature on the path: unwind the previous occurrence and
        # fold its fractions into the incoming ones
        if pi >= 0:
            for k in range(1, len(m.f)):
                if m.f[k] == pi:
                    pz = pz * m.z[k]
                    po = po * m.o[k]
                    m = _unwind(m, k)
                    break
        _extend(m, pz, po, pi)
        f = int(feature[node])
        if f < 0:  # leaf
            v = float(value[node])
            if v != 0.0:
                for i in range(1, len(m.f)):
                    w = _unwound_sum(m, i)
                    phi[:, m.f[i]] += w * (m.o[i] - m.z[i]) * v
            return
        left, right = 2 * node + 1, 2 * node + 2
        if cat_mask is not None and cat_mask[node].any():
            B = cat_mask.shape[1]
            col = X[:, f]
            code = np.floor(col)
            valid = np.isfinite(col) & (code >= 0) & (code < B)
            idx = np.where(valid, code, 0).astype(np.int64)
            go_left = (valid & (cat_mask[node][idx] > 0)).astype(np.float64)
        else:
            go_left = (X[:, f] <= threshold[node]).astype(np.float64)
        c = max(float(cover[node]), 1e-12)
        zl = float(cover[left]) / c
        zr = float(cover[right]) / c
        recurse(left, m, zl, go_left, f)
        recurse(right, m, zr, 1.0 - go_left, f)

    ones = np.ones(N, np.float64)
    recurse(0, _Path([], [], [], []), 1.0, ones, -1)

    # bias column: E[tree] = cover-weighted leaf average
    leaves = feature < 0
    w = np.where(leaves, cover, 0.0)
    total = w.sum()
    if total > 0:
        phi[:, -1] += float((w * value).sum() / total)


def forest_shap(feature: np.ndarray, threshold_value: np.ndarray,
                leaf_value: np.ndarray, cover: np.ndarray,
                init_score: np.ndarray, X: np.ndarray,
                cat_mask: np.ndarray | None = None) -> np.ndarray:
    """(N, K, F+1) SHAP contributions for a stacked forest.

    feature/threshold_value/leaf_value/cover: (T, K, M); init_score: (K,).
    Column F (last) is the expected value (bias), and for every row
    ``contrib.sum(-1) == raw_score`` (checked by tests).
    """
    X = np.asarray(X, np.float64)
    T, K, M = feature.shape
    N, F = X.shape
    out = np.zeros((N, K, F + 1), np.float64)
    for k in range(K):
        phi = out[:, k, :]
        phi[:, -1] += float(init_score[k])
        for t in range(T):
            _tree_shap(feature[t, k], threshold_value[t, k], leaf_value[t, k],
                       cover[t, k], X, phi,
                       cat_mask=None if cat_mask is None else cat_mask[t, k])
    return out
