// Native runtime ops for synapseml_tpu.
//
// The reference ships C++ engines for its hot host-side loops (LightGBM's
// dataset marshaling, VW's parser+hasher — SURVEY.md §1 L0). The TPU compute
// path is XLA; what stays on the host is feature hashing and tokenization,
// implemented here and bound via ctypes (no pybind11 in this toolchain).
//
// Build: g++ -O3 -shared -fPIC -std=c++17 native_ops.cpp -o libnative_ops.so

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr uint32_t C1 = 0xcc9e2d51u;
constexpr uint32_t C2 = 0x1b873593u;

inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85ebca6bu;
  h ^= h >> 13;
  h *= 0xc2b2ae35u;
  h ^= h >> 16;
  return h;
}

uint32_t murmur3_32(const uint8_t* data, int64_t len, uint32_t seed) {
  uint32_t h = seed;
  const int64_t nblocks = len / 4;
  for (int64_t i = 0; i < nblocks; i++) {
    uint32_t k;
    std::memcpy(&k, data + 4 * i, 4);  // little-endian hosts only (x86/ARM LE)
    k *= C1;
    k = rotl32(k, 15);
    k *= C2;
    h ^= k;
    h = rotl32(h, 13);
    h = h * 5 + 0xe6546b64u;
  }
  const uint8_t* tail = data + nblocks * 4;
  uint32_t k = 0;
  switch (len & 3) {
    case 3: k ^= static_cast<uint32_t>(tail[2]) << 16; [[fallthrough]];
    case 2: k ^= static_cast<uint32_t>(tail[1]) << 8;  [[fallthrough]];
    case 1:
      k ^= tail[0];
      k *= C1;
      k = rotl32(k, 15);
      k *= C2;
      h ^= k;
  }
  h ^= static_cast<uint32_t>(len);
  return fmix32(h);
}

inline bool is_token_char(uint8_t c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
         (c >= '0' && c <= '9') || c == '_';
}

inline uint8_t lower(uint8_t c) {
  return (c >= 'A' && c <= 'Z') ? c + 32 : c;
}

}  // namespace

extern "C" {

// Single hash (parity check with the Python implementation).
uint32_t nat_murmur3_32(const uint8_t* data, int64_t len, uint32_t seed) {
  return murmur3_32(data, len, seed);
}

// Batch: n strings as concatenated bytes + (n+1) offsets -> n hashes.
void nat_murmur3_batch(const uint8_t* data, const int64_t* offsets, int64_t n,
                       uint32_t seed, uint32_t mask, uint32_t* out) {
  for (int64_t i = 0; i < n; i++) {
    out[i] = murmur3_32(data + offsets[i], offsets[i + 1] - offsets[i], seed) & mask;
  }
}

// Tokenize one document ([A-Za-z0-9_]+ runs, optional ASCII lowercase) and
// hash every token: out gets up to max_tokens bucket ids; returns the count.
// Matches hash_feature(token, namespace_seed=seed) & mask on the Python side.
int64_t nat_doc_token_hashes(const uint8_t* text, int64_t len, uint32_t seed,
                             uint32_t mask, int32_t do_lower, uint32_t* out,
                             int64_t max_tokens) {
  int64_t count = 0;
  int64_t i = 0;
  std::vector<uint8_t> buf(256);
  while (i < len && count < max_tokens) {
    while (i < len && !is_token_char(text[i])) i++;
    if (i >= len) break;
    int64_t tlen = 0;
    while (i < len && is_token_char(text[i])) {
      if (tlen >= static_cast<int64_t>(buf.size())) buf.resize(buf.size() * 2);
      buf[tlen++] = do_lower ? lower(text[i]) : text[i];
      i++;
    }
    out[count++] = murmur3_32(buf.data(), tlen, seed) & mask;
  }
  return count;
}

// Batch variant over documents (concatenated bytes + offsets). out is
// [n_docs * max_tokens_per_doc]; counts receives per-doc token counts.
void nat_docs_token_hashes(const uint8_t* data, const int64_t* offsets,
                           int64_t n_docs, uint32_t seed, uint32_t mask,
                           int32_t do_lower, uint32_t* out,
                           int64_t max_tokens_per_doc, int64_t* counts) {
  for (int64_t d = 0; d < n_docs; d++) {
    counts[d] = nat_doc_token_hashes(
        data + offsets[d], offsets[d + 1] - offsets[d], seed, mask, do_lower,
        out + d * max_tokens_per_doc, max_tokens_per_doc);
  }
}

}  // extern "C"
