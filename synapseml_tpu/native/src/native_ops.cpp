// Native runtime ops for synapseml_tpu.
//
// The reference ships C++ engines for its hot host-side loops (LightGBM's
// dataset marshaling, VW's parser+hasher — SURVEY.md §1 L0). The TPU compute
// path is XLA; what stays on the host is feature hashing and tokenization,
// implemented here and bound via ctypes (no pybind11 in this toolchain).
//
// Build: g++ -O3 -shared -fPIC -std=c++17 native_ops.cpp -o libnative_ops.so

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t C1 = 0xcc9e2d51u;
constexpr uint32_t C2 = 0x1b873593u;

inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85ebca6bu;
  h ^= h >> 13;
  h *= 0xc2b2ae35u;
  h ^= h >> 16;
  return h;
}

uint32_t murmur3_32(const uint8_t* data, int64_t len, uint32_t seed) {
  uint32_t h = seed;
  const int64_t nblocks = len / 4;
  for (int64_t i = 0; i < nblocks; i++) {
    uint32_t k;
    std::memcpy(&k, data + 4 * i, 4);  // little-endian hosts only (x86/ARM LE)
    k *= C1;
    k = rotl32(k, 15);
    k *= C2;
    h ^= k;
    h = rotl32(h, 13);
    h = h * 5 + 0xe6546b64u;
  }
  const uint8_t* tail = data + nblocks * 4;
  uint32_t k = 0;
  switch (len & 3) {
    case 3: k ^= static_cast<uint32_t>(tail[2]) << 16; [[fallthrough]];
    case 2: k ^= static_cast<uint32_t>(tail[1]) << 8;  [[fallthrough]];
    case 1:
      k ^= tail[0];
      k *= C1;
      k = rotl32(k, 15);
      k *= C2;
      h ^= k;
  }
  h ^= static_cast<uint32_t>(len);
  return fmix32(h);
}

inline bool is_token_char(uint8_t c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
         (c >= '0' && c <= '9') || c == '_';
}

inline uint8_t lower(uint8_t c) {
  return (c >= 'A' && c <= 'Z') ? c + 32 : c;
}

}  // namespace

extern "C" {

// Single hash (parity check with the Python implementation).
uint32_t nat_murmur3_32(const uint8_t* data, int64_t len, uint32_t seed) {
  return murmur3_32(data, len, seed);
}

// Batch: n strings as concatenated bytes + (n+1) offsets -> n hashes.
void nat_murmur3_batch(const uint8_t* data, const int64_t* offsets, int64_t n,
                       uint32_t seed, uint32_t mask, uint32_t* out) {
  for (int64_t i = 0; i < n; i++) {
    out[i] = murmur3_32(data + offsets[i], offsets[i + 1] - offsets[i], seed) & mask;
  }
}

// Tokenize one document ([A-Za-z0-9_]+ runs, optional ASCII lowercase) and
// hash every token: out gets up to max_tokens bucket ids; returns the count.
// Matches hash_feature(token, namespace_seed=seed) & mask on the Python side.
int64_t nat_doc_token_hashes(const uint8_t* text, int64_t len, uint32_t seed,
                             uint32_t mask, int32_t do_lower, uint32_t* out,
                             int64_t max_tokens) {
  int64_t count = 0;
  int64_t i = 0;
  std::vector<uint8_t> buf(256);
  while (i < len && count < max_tokens) {
    while (i < len && !is_token_char(text[i])) i++;
    if (i >= len) break;
    int64_t tlen = 0;
    while (i < len && is_token_char(text[i])) {
      if (tlen >= static_cast<int64_t>(buf.size())) buf.resize(buf.size() * 2);
      buf[tlen++] = do_lower ? lower(text[i]) : text[i];
      i++;
    }
    out[count++] = murmur3_32(buf.data(), tlen, seed) & mask;
  }
  return count;
}

// Batch variant over documents (concatenated bytes + offsets). out is
// [n_docs * max_tokens_per_doc]; counts receives per-doc token counts.
void nat_docs_token_hashes(const uint8_t* data, const int64_t* offsets,
                           int64_t n_docs, uint32_t seed, uint32_t mask,
                           int32_t do_lower, uint32_t* out,
                           int64_t max_tokens_per_doc, int64_t* counts) {
  for (int64_t d = 0; d < n_docs; d++) {
    counts[d] = nat_doc_token_hashes(
        data + offsets[d], offsets[d + 1] - offsets[d], seed, mask, do_lower,
        out + d * max_tokens_per_doc, max_tokens_per_doc);
  }
}

// Row binning — the GBDT Dataset-construction hot loop (reference analog:
// the Swig row marshaling behind LGBM_DatasetPushRowsWithMetadata,
// StreamingPartitionTask.scala:220). x is [n, f] float32 row-major;
// bounds is [f, b] float64 ascending upper boundaries (padded with +inf);
// is_cat[f] marks identity-binned categorical columns. out[n, f] int32:
// searchsorted-right over bounds, NaN/invalid -> nan_bin. Multithreaded
// over row blocks (each thread writes a disjoint slice).
void nat_bin_rows(const float* x, const double* bounds, int64_t n, int64_t f,
                  int64_t b, int32_t nan_bin, int32_t max_bin,
                  const uint8_t* is_cat, int32_t* out, int32_t n_threads) {
  auto work = [&](int64_t r0, int64_t r1) {
    for (int64_t r = r0; r < r1; r++) {
      const float* row = x + r * f;
      int32_t* orow = out + r * f;
      for (int64_t j = 0; j < f; j++) {
        const float v = row[j];
        if (std::isnan(v)) {
          orow[j] = nan_bin;
          continue;
        }
        if (is_cat[j]) {
          const double code = std::floor(static_cast<double>(v));
          orow[j] = (code >= 0 && code < max_bin && std::isfinite(v))
                        ? static_cast<int32_t>(code)
                        : nan_bin;
          continue;
        }
        // branchless-ish binary search: first index with bounds[idx] >= v is
        // lower_bound; searchsorted(side='right') is first bounds[idx] > v
        const double* bj = bounds + j * b;
        int64_t lo = 0, hi = b;
        const double vd = static_cast<double>(v);
        while (lo < hi) {
          const int64_t mid = (lo + hi) >> 1;
          if (bj[mid] <= vd) {
            lo = mid + 1;
          } else {
            hi = mid;
          }
        }
        orow[j] = static_cast<int32_t>(lo);
      }
    }
  };
  if (n_threads <= 1 || n < 4096) {
    work(0, n);
    return;
  }
  std::vector<std::thread> pool;
  const int64_t block = (n + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; t++) {
    const int64_t r0 = t * block;
    const int64_t r1 = std::min(n, r0 + block);
    if (r0 < r1) pool.emplace_back(work, r0, r1);
  }
  for (auto& th : pool) th.join();
}

}  // extern "C"
