"""Native (C++) host runtime — the framework's L0 layer.

The reference loads prebuilt C++ engines through ``NativeLoader.java``
(SURVEY.md §1 L1). Here the native library is small (the device compute is
XLA; the host hot loops are hashing/tokenization), builds from source with
g++ on first use, binds via ctypes, and every entry point has a pure-Python
fallback so the package works without a toolchain.

Public surface:
  * ``available()`` — did the library build/load?
  * ``murmur3_batch(names, seed, num_bits)`` — vectorized VW feature hashing
  * ``docs_token_hashes(texts, seed, num_bits, lower)`` — tokenize+hash whole
    documents in one call (TextFeaturizer / VW text path)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = ["available", "murmur3_32_native", "murmur3_batch", "docs_token_hashes",
           "bin_rows", "library_path"]

_LOCK = threading.Lock()
_LIB = None
_TRIED = False

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "src",
                    "native_ops.cpp")


def library_path() -> str:
    # keyed by source digest, not mtime: a cached build of an OLDER source
    # (wheel installs preserve mtimes) must never load — a missing symbol
    # would raise out of the ctypes binding instead of falling back
    import hashlib

    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:12]
    cache = os.environ.get("SYNAPSEML_TPU_NATIVE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "synapseml_tpu", "native")
    os.makedirs(cache, exist_ok=True)
    # prune superseded digests, but only STALE ones (>30 days unused):
    # immediate deletion would let two package versions sharing the cache
    # evict each other's builds every startup — or even race a concurrent
    # process between its _build() and CDLL()
    import time

    cutoff = time.time() - 30 * 86400
    for old in os.listdir(cache):
        if (old.startswith("libnative_ops") and old.endswith(".so")
                and digest not in old):
            path = os.path.join(cache, old)
            try:
                if os.path.getmtime(path) < cutoff:
                    os.remove(path)
            except OSError:
                pass
    return os.path.join(cache, f"libnative_ops-{digest}.so")


def _build() -> str | None:
    try:
        out = library_path()  # content-addressed: existing file IS this source
    except OSError:  # source stripped from the install: pure-Python fallback
        return None
    if os.path.exists(out):
        return out
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", out],
            check=True, capture_output=True, timeout=120)
        return out
    except (OSError, subprocess.SubprocessError):
        return None


def _load():
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.nat_murmur3_32.restype = ctypes.c_uint32
        lib.nat_murmur3_32.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                       ctypes.c_uint32]
        lib.nat_murmur3_batch.restype = None
        lib.nat_murmur3_batch.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_uint32, ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint32)]
        lib.nat_docs_token_hashes.restype = None
        lib.nat_docs_token_hashes.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_uint32, ctypes.c_uint32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64)]
        lib.nat_bin_rows.restype = None
        lib.nat_bin_rows.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
        _LIB = lib
        return _LIB


def available() -> bool:
    return _load() is not None


def murmur3_32_native(data: bytes, seed: int = 0) -> int | None:
    lib = _load()
    if lib is None:
        return None
    return int(lib.nat_murmur3_32(data, len(data), seed & 0xFFFFFFFF))


def _pack(strings: list[bytes]) -> tuple[bytes, np.ndarray]:
    offsets = np.zeros(len(strings) + 1, np.int64)
    np.cumsum([len(s) for s in strings], out=offsets[1:])
    return b"".join(strings), offsets


def murmur3_batch(names: list[str], seed: int = 0, num_bits: int = 32) -> np.ndarray | None:
    """n feature names -> n masked hashes; None when the library is absent."""
    lib = _load()
    if lib is None:
        return None
    data, offsets = _pack([n.encode("utf-8") for n in names])
    out = np.zeros(len(names), np.uint32)
    mask = (1 << num_bits) - 1 if num_bits < 32 else 0xFFFFFFFF
    lib.nat_murmur3_batch(
        data, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(names), seed & 0xFFFFFFFF, mask,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
    return out


def docs_token_hashes(texts: list[str], seed: int = 0, num_bits: int = 18,
                      lower: bool = True, max_tokens_per_doc: int = 4096):
    """Tokenize+hash documents natively -> list of per-doc bucket arrays;
    None when the library is absent."""
    lib = _load()
    if lib is None:
        return None
    data, offsets = _pack([t.encode("utf-8") for t in texts])
    n = len(texts)
    out = np.zeros(n * max_tokens_per_doc, np.uint32)
    counts = np.zeros(n, np.int64)
    mask = (1 << num_bits) - 1 if num_bits < 32 else 0xFFFFFFFF
    lib.nat_docs_token_hashes(
        data, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
        seed & 0xFFFFFFFF, mask, 1 if lower else 0,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        max_tokens_per_doc,
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    return [out[i * max_tokens_per_doc : i * max_tokens_per_doc + counts[i]].copy()
            for i in range(n)]


def bin_rows(x: np.ndarray, boundaries: np.ndarray, nan_bin: int, max_bin: int,
             categorical: tuple = (), n_threads: int | None = None):
    """Row-major multithreaded binning (the GBDT Dataset-construction hot
    loop; reference analog: the Swig marshaling behind
    ``LGBM_DatasetPushRowsWithMetadata``). searchsorted-right semantics per
    column; NaN -> ``nan_bin``; categorical columns bin by identity. Returns
    (N, F) int32, or None when the library is absent."""
    lib = _load()
    if lib is None:
        return None
    xf = np.ascontiguousarray(x, dtype=np.float32)
    n, f = xf.shape
    bounds = np.ascontiguousarray(boundaries, dtype=np.float64)
    if bounds.ndim != 2 or bounds.shape[0] != f:
        raise ValueError(f"boundaries shape {bounds.shape} does not match "
                         f"feature count {f}")
    is_cat = np.zeros(f, np.uint8)
    if categorical:
        idx = np.asarray(categorical, np.int64)
        if idx.size and (idx.min() < 0 or idx.max() >= f):
            raise ValueError(f"categorical indices {sorted(categorical)} out "
                             f"of range [0, {f})")
        is_cat[idx] = 1
    out = np.empty((n, f), np.int32)
    if n_threads is None:
        n_threads = min(os.cpu_count() or 1, 16)
    lib.nat_bin_rows(
        xf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        n, f, bounds.shape[1], nan_bin, max_bin,
        is_cat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n_threads)
    return out
