"""Brute-force matmul KNN + conditional KNN.

Reference surface: ``KNN.scala:49`` / ``ConditionalKNN`` fitted on a
features+values DataFrame, transform adds a column of the k best matches per
query row (ref ``nn/KNN.scala``, ``ConditionalBallTree`` restricts candidates
to per-query allowed labels).

TPU design: squared L2 distance decomposes as |q|^2 - 2 q·x + |x|^2, so the
hot loop is ONE [Q, N] matmul (MXU) + top_k; queries stream through in fixed
padded batches so every batch reuses the same executable. Conditional
filtering is a mask added to the distance matrix, not a tree walk.

Scoring is the SHARED per-shard kernel in ``retrieval/scorer.py`` (the
retrieval serving plane's engine) — seed KNN and the sharded
``VectorIndexModel`` cannot drift, and because the index matrix is a traced
ARGUMENT there, swapping a model's ``index`` param never leaves stale
executables behind (nothing instance-specific is captured).
"""

from __future__ import annotations

import numpy as np

from ..core import batching as cb
from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model
from ..core.utils import stack_vector_column as _stack_features
from ..retrieval.scorer import INF as _INF
from ..retrieval.scorer import score_batches as _score_shard

__all__ = ["KNN", "KNNModel", "ConditionalKNN", "ConditionalKNNModel"]


class _KNNBase(Estimator):
    features_col = Param("features_col", "feature vector column", default="features")
    values_col = Param("values_col", "payload column returned with matches",
                       default="values")
    label_col = Param("label_col", "conditioner label column (conditional only)",
                      default="labels")
    output_col = Param("output_col", "matches column", default="output")
    k = Param("k", "number of neighbors", default=5, converter=TypeConverters.to_int)
    query_batch = Param("query_batch", "padded query rows per device batch",
                        default=256, converter=TypeConverters.to_int)


class KNN(_KNNBase):
    """(ref ``nn/KNN.scala:49``)"""

    feature_name = "nn"

    def _fit(self, df: DataFrame) -> "KNNModel":
        self.require_columns(df, self.get("features_col"), self.get("values_col"))
        X = _stack_features(df.collect_column(self.get("features_col")))
        vals = np.asarray(df.collect_column(self.get("values_col")))
        return KNNModel(index=X, values=vals,
                        features_col=self.get("features_col"),
                        output_col=self.get("output_col"),
                        k=self.get("k"), query_batch=self.get("query_batch"))


class KNNModel(Model):
    index = ComplexParam("index", "[N, D] indexed feature matrix")
    values = ComplexParam("values", "payload per indexed row")
    labels = ComplexParam("labels", "conditioner label per indexed row", default=None)
    features_col = Param("features_col", "feature vector column", default="features")
    output_col = Param("output_col", "matches column", default="output")
    k = Param("k", "number of neighbors", default=5, converter=TypeConverters.to_int)
    query_batch = Param("query_batch", "padded query rows per device batch",
                        default=256, converter=TypeConverters.to_int)

    def _match_bias(self, p, s: int, e: int) -> np.ndarray | None:
        """[e-s, N] additive bias (0 = allowed) for one query batch;
        None (plain KNN) means everything is allowed — no bias matrix is
        materialized or shipped to the device."""
        return None

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("features_col"))
        X = np.ascontiguousarray(self.get("index"), np.float32)
        x_sq = np.sum(X * X, axis=1, dtype=np.float32)
        vals = self.get("values")
        labels = self.get("labels")
        B = self.get("query_batch")
        k = self.get("k")

        def per_part(p):
            Q = _stack_features(p[self.get("features_col")])
            n = len(Q)
            matches = np.empty(n, dtype=object)
            # the shared retrieval kernel: ladder-bucketed query batches,
            # ONE executable per (bucket, index-shape) across the process
            dist, idx = _score_shard(
                Q, X, k, x_sq=x_sq, query_batch=B,
                bias_fn=lambda s, e: self._match_bias(p, s, e))
            for i in range(n):
                row = []
                for d, j in zip(dist[i], idx[i]):
                    if d >= _INF / 2:  # filtered out (conditional)
                        continue
                    match = {"value": vals[j],
                             "distance": float(np.sqrt(max(d, 0.0))),
                             "index": int(j)}
                    if labels is not None:
                        match["label"] = labels[j]
                    row.append(match)
                matches[i] = row
            q = dict(p)
            q[self.get("output_col")] = matches
            return q

        return df.map_partitions(per_part)


class ConditionalKNN(_KNNBase):
    """(ref ``nn/ConditionalKNN.scala``) — neighbors restricted per query to
    rows whose label is in the query's ``conditioner`` set."""

    feature_name = "nn"

    conditioner_col = Param("conditioner_col", "column of allowed-label sets",
                            default="conditioner")

    def _fit(self, df: DataFrame) -> "ConditionalKNNModel":
        self.require_columns(df, self.get("features_col"), self.get("values_col"),
                             self.get("label_col"))
        X = _stack_features(df.collect_column(self.get("features_col")))
        vals = np.asarray(df.collect_column(self.get("values_col")))
        labels = np.asarray(df.collect_column(self.get("label_col")))
        return ConditionalKNNModel(index=X, values=vals, labels=labels,
                                   features_col=self.get("features_col"),
                                   output_col=self.get("output_col"),
                                   conditioner_col=self.get("conditioner_col"),
                                   k=self.get("k"), query_batch=self.get("query_batch"))


class ConditionalKNNModel(KNNModel):
    conditioner_col = Param("conditioner_col", "column of allowed-label sets",
                            default="conditioner")

    def _match_bias(self, p, s: int, e: int) -> np.ndarray:
        labels = np.asarray(self.get("labels"))
        conds = p[self.get("conditioner_col")][s:e]
        bias = np.full((e - s, len(labels)), _INF, np.float32)
        for i in range(e - s):
            allowed = conds[i]
            allowed = {allowed} if np.isscalar(allowed) else set(np.asarray(allowed).tolist())
            bias[i, np.isin(labels, list(allowed))] = 0.0
        return bias

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("conditioner_col"))
        return super()._transform(df)
