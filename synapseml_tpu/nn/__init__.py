"""Nearest neighbors (reference ``core/.../nn/`` — SURVEY.md §2.5).

The reference builds a serializable BallTree (``nn/BallTree.scala:33-280``)
because CPU pruning beats brute force there. On TPU the opposite holds
(SURVEY.md §7 step 8): one [Q, N] distance matmul on the MXU + `lax.top_k`
beats tree traversal's irregular control flow by orders of magnitude, and the
index is just the feature matrix resident in HBM.
"""

from .knn import KNN, KNNModel, ConditionalKNN, ConditionalKNNModel

__all__ = ["KNN", "KNNModel", "ConditionalKNN", "ConditionalKNNModel"]
