"""Isolation forest: random-split trees; anomaly score 2^(-E[pathlen]/c(n)).

Param surface mirrors the reference wrapper (``IsolationForest.scala:19-74``:
numEstimators, maxSamples, maxFeatures, bootstrap, contamination,
scoreCol/predictedLabelCol).
"""

from __future__ import annotations

import numpy as np

from ..core.batching import (default_bucketer, get_compiled_cache,
                             instance_token, pad_rows)
from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Estimator, Model
from ..core.utils import stack_vector_column

__all__ = ["IsolationForest", "IsolationForestModel"]

SCORE_FN_ID = "iforest.score"
_MAX_SCORE_ROWS = 1024


def _c_factor(n: float) -> float:
    """Average BST unsuccessful-search path length c(n)."""
    if n <= 1:
        return 0.0
    h = np.log(n - 1) + np.euler_gamma
    return 2.0 * h - 2.0 * (n - 1) / n


def _build_tree(X: np.ndarray, rng, height_limit: int, feature_idx: np.ndarray):
    """Arrays: feature[node], threshold[node], left/right child (-1 = leaf),
    size[node] (samples reaching the node; leaves adjust path length by c(size))."""
    feature, threshold, left, right, size = [], [], [], [], []

    def grow(rows: np.ndarray, depth: int) -> int:
        node = len(feature)
        feature.append(-1)
        threshold.append(0.0)
        left.append(-1)
        right.append(-1)
        size.append(len(rows))
        if depth >= height_limit or len(rows) <= 1:
            return node
        cols = feature_idx[rng.permutation(len(feature_idx))]
        for f in cols:
            vals = X[rows, f]
            lo, hi = vals.min(), vals.max()
            if hi > lo:
                split = rng.uniform(lo, hi)
                feature[node] = int(f)
                threshold[node] = float(split)
                mask = vals < split
                left[node] = grow(rows[mask], depth + 1)
                right[node] = grow(rows[~mask], depth + 1)
                return node
        return node  # all candidate features constant -> leaf

    grow(np.arange(len(X)), 0)
    return (np.asarray(feature, np.int32), np.asarray(threshold, np.float32),
            np.asarray(left, np.int32), np.asarray(right, np.int32),
            np.asarray(size, np.int32))


def _c_factor_vec(n: np.ndarray) -> np.ndarray:
    n = np.asarray(n, np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        h = np.log(np.maximum(n - 1, 1e-12)) + np.euler_gamma
        c = 2.0 * h - 2.0 * (n - 1) / np.maximum(n, 1e-12)
    return np.where(n <= 1, 0.0, c)


def _path_lengths(X: np.ndarray, tree) -> np.ndarray:
    feature, threshold, left, right, size = tree
    n = len(X)
    node = np.zeros(n, np.int32)
    depth = np.zeros(n, np.float32)
    active = feature[node] >= 0
    while np.any(active):
        rows = np.nonzero(active)[0]
        cur = node[rows]
        f = feature[cur]
        go_left = X[rows, f] < threshold[cur]
        node[rows] = np.where(go_left, left[cur], right[cur])
        depth[rows] += 1.0
        active = feature[node] >= 0
    return depth + _c_factor_vec(size[node]).astype(np.float32)


def _pack_trees(trees) -> tuple:
    """Node-padded [T, N_max] tree tables for the batched traversal.

    Padding nodes are leaves (feature -1, c-factor 0) so a padded tree
    behaves like the ragged original; the per-node leaf adjustment
    ``c(size)`` is precomputed here so the compiled fn never touches sizes."""
    T = len(trees)
    N = max(len(t[0]) for t in trees)
    feature = np.full((T, N), -1, np.int32)
    threshold = np.zeros((T, N), np.float32)
    left = np.zeros((T, N), np.int32)
    right = np.zeros((T, N), np.int32)
    c_leaf = np.zeros((T, N), np.float32)
    for i, (f, th, l, r, s) in enumerate(trees):
        k = len(f)
        feature[i, :k] = f
        threshold[i, :k] = th
        left[i, :k] = l
        right[i, :k] = r
        c_leaf[i, :k] = _c_factor_vec(s)
    return feature, threshold, left, right, c_leaf


def _build_score_fn(packed, height: int, c_norm: float):
    """One executable per (model, bucket): every tree walks its fixed
    ``height`` steps in lockstep over the whole padded batch — the ragged
    per-tree/per-row Python recursion becomes a [T, N] gather per step."""
    import jax
    import jax.numpy as jnp

    feature, threshold, left, right, c_leaf = (jnp.asarray(a) for a in packed)

    def score(X):
        B = X.shape[0]
        rows = jnp.arange(B)

        def one_tree(f, th, l, r, cl):
            def step(_, carry):
                node, depth = carry
                active = f[node] >= 0
                col = jnp.clip(f[node], 0, X.shape[1] - 1)
                go_left = X[rows, col] < th[node]
                nxt = jnp.where(go_left, l[node], r[node])
                return (jnp.where(active, nxt, node),
                        depth + active.astype(jnp.float32))

            node, depth = jax.lax.fori_loop(
                0, height, step,
                (jnp.zeros(B, jnp.int32), jnp.zeros(B, jnp.float32)))
            return depth + cl[node]

        depths = jax.vmap(one_tree)(feature, threshold, left, right, c_leaf)
        return jnp.power(2.0, -depths.mean(axis=0) / c_norm)

    return jax.jit(score)


class IsolationForest(Estimator):
    feature_name = "isolationforest"

    features_col = Param("features_col", "feature matrix column", default="features")
    num_estimators = Param("num_estimators", "number of trees", default=100,
                           converter=TypeConverters.to_int)
    max_samples = Param("max_samples", "samples per tree (<=1.0: fraction)",
                        default=256.0, converter=TypeConverters.to_float)
    max_features = Param("max_features", "features per tree (<=1.0: fraction)",
                         default=1.0, converter=TypeConverters.to_float)
    bootstrap = Param("bootstrap", "sample with replacement", default=False,
                      converter=TypeConverters.to_bool)
    contamination = Param("contamination", "expected anomaly fraction (0 = "
                          "score only, threshold 0.5)", default=0.0,
                          converter=TypeConverters.to_float)
    score_col = Param("score_col", "anomaly score column", default="outlierScore")
    predicted_label_col = Param("predicted_label_col", "0/1 anomaly column",
                                default="predictedLabel")
    random_seed = Param("random_seed", "rng seed", default=1,
                        converter=TypeConverters.to_int)

    def _fit(self, df: DataFrame) -> "IsolationForestModel":
        self.require_columns(df, self.get("features_col"))
        X = stack_vector_column(df.collect_column(self.get("features_col")))
        n, d = X.shape
        rng = np.random.default_rng(self.get("random_seed"))
        ms = self.get("max_samples")
        n_sub = int(round(ms * n)) if ms <= 1.0 else int(min(ms, n))
        n_sub = max(n_sub, 2)
        mf = self.get("max_features")
        n_feat = max(int(round(mf * d)) if mf <= 1.0 else int(min(mf, d)), 1)
        height = int(np.ceil(np.log2(max(n_sub, 2))))
        trees = []
        for _ in range(self.get("num_estimators")):
            rows = (rng.integers(0, n, n_sub) if self.get("bootstrap")
                    else rng.permutation(n)[:n_sub])
            feats = rng.permutation(d)[:n_feat]
            trees.append(_build_tree(X[rows], rng, height, feats))
        model = IsolationForestModel(
            trees=trees, subsample_size=n_sub,
            features_col=self.get("features_col"),
            score_col=self.get("score_col"),
            predicted_label_col=self.get("predicted_label_col"))
        contamination = self.get("contamination")
        if contamination > 0:
            scores = model._scores(X)
            model.set(threshold=float(np.quantile(scores, 1.0 - contamination)))
        return model


class IsolationForestModel(Model):
    trees = ComplexParam("trees", "list of flat tree arrays")
    subsample_size = Param("subsample_size", "samples per tree at fit",
                           converter=TypeConverters.to_int)
    threshold = Param("threshold", "score threshold for the 0/1 label", default=0.5,
                      converter=TypeConverters.to_float)
    features_col = Param("features_col", "feature matrix column", default="features")
    score_col = Param("score_col", "anomaly score column", default="outlierScore")
    predicted_label_col = Param("predicted_label_col", "0/1 anomaly column",
                                default="predictedLabel")

    def _scores_reference(self, X: np.ndarray) -> np.ndarray:
        """Serial numpy traversal — the parity oracle for the compiled path."""
        X = np.asarray(X, np.float32)
        trees = self.get("trees")
        depths = np.mean([_path_lengths(X, t) for t in trees], axis=0)
        c = _c_factor(float(self.get("subsample_size")))
        return np.power(2.0, -depths / max(c, 1e-9))

    def _scores(self, X: np.ndarray) -> np.ndarray:
        """Anomaly scores on the shared ladder: one CompiledCache executable
        per bucket (``SCORE_FN_ID`` misses are the compile bill), edge-padded
        chunks so padding rows traverse real feature values."""
        X = np.ascontiguousarray(np.asarray(X, np.float32))
        n = len(X)
        if n == 0:
            return np.zeros(0, np.float64)
        packed = self.__dict__.get("_iforest_packed")
        if packed is None:
            packed = _pack_trees(self.get("trees"))
            self.__dict__["_iforest_packed"] = packed
        n_sub = float(self.get("subsample_size"))
        height = int(np.ceil(np.log2(max(n_sub, 2))))
        c_norm = max(_c_factor(n_sub), 1e-9)
        cache = get_compiled_cache()
        out = np.empty(n, np.float64)
        for start, stop, bucket in default_bucketer().slices(
                n, max_rows=_MAX_SCORE_ROWS):
            chunk = pad_rows(X[start:stop], bucket, mode="edge")

            def build(packed=packed, height=height, c_norm=c_norm):
                return _build_score_fn(packed, height, c_norm)

            exe = cache.get(SCORE_FN_ID, (bucket, X.shape[1]), build,
                            instance=instance_token(self),
                            dtype=str(chunk.dtype))
            y = np.asarray(exe(chunk), np.float64)
            out[start:stop] = y[: stop - start]
        return out

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("features_col"))

        def score(p):
            return self._scores(stack_vector_column(p[self.get("features_col")]))

        out = df.with_column(self.get("score_col"), score)
        thr = self.get("threshold")
        return out.with_column(
            self.get("predicted_label_col"),
            lambda p: (np.asarray(p[self.get("score_col")]) >= thr).astype(np.int32))
