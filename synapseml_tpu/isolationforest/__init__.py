"""Isolation forest anomaly detection.

Reference: ``isolationforest/IsolationForest.scala:19-74`` — a thin wrapper
over com.linkedin.isolation-forest (SURVEY.md §2.5). Here the algorithm is
native to the framework: trees fit on host numpy (cheap, data-subsampled),
stored as flat arrays, and scored by a vectorized traversal.
"""

from .iforest import IsolationForest, IsolationForestModel

__all__ = ["IsolationForest", "IsolationForestModel"]
