"""Binary / image file data sources.

Reference: ``core/.../io/binary/BinaryFileFormat.scala`` (binary-file
DataSource: path/length/modificationTime/content rows) and
``org/apache/spark/ml/source/image/PatchedImageFileFormat.scala`` (image data
source decoding to the Spark image schema). Here the rows land in the columnar
DataFrame plane; images decode to [H, W, C] uint8 numpy (the layout
``image.ImageTransformer`` consumes).
"""

from __future__ import annotations

import glob as _glob
import io as _io
import os

import numpy as np

from ..core.dataframe import DataFrame

__all__ = ["read_binary_files", "read_image_files", "read_csv", "write_csv",
           "read_jsonl", "write_jsonl", "resolve_input_paths"]

_IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".tif", ".tiff", ".webp")


def _resolve_paths(path: str, recursive: bool, exts: tuple[str, ...] | None) -> list[str]:
    if os.path.isdir(path):
        pattern = os.path.join(path, "**" if recursive else "", "*")
        paths = _glob.glob(pattern, recursive=recursive)
    else:
        paths = _glob.glob(path, recursive=recursive)
    out = [p for p in paths if os.path.isfile(p)]
    if exts is not None:
        out = [p for p in out if p.lower().endswith(exts)]
    return sorted(out)


def resolve_input_paths(path: str, what: str,
                        exts: tuple[str, ...] | None = None) -> list[str]:
    """THE glob-or-literal input resolver: a glob pattern or directory lists
    through ``_resolve_paths``; a literal filename passes through untouched.
    Shared by the eager tabular readers below and the streaming plane's
    ``data.ShardedSource``, so the two planes can never list differently."""
    is_glob = any(ch in path for ch in "*?[")
    paths = (_resolve_paths(path, recursive=True, exts=exts)
             if is_glob or os.path.isdir(path) else [path])
    if not paths:
        raise FileNotFoundError(f"no {what} files match {path!r}")
    return paths


def _partitioned(rows: list[dict], num_partitions: int) -> DataFrame:
    if not rows:
        return DataFrame.from_rows([], num_partitions=1)
    return DataFrame.from_rows(rows, num_partitions=min(num_partitions, len(rows)))


def read_binary_files(path: str, recursive: bool = True, num_partitions: int = 1,
                      extensions: tuple[str, ...] | None = None) -> DataFrame:
    """Directory/glob -> rows of (path, length, modification_time, content).

    The ``BinaryFileFormat`` schema; ``content`` is raw bytes."""
    rows = []
    for p in _resolve_paths(path, recursive, extensions):
        st = os.stat(p)
        with open(p, "rb") as f:
            content = f.read()
        rows.append({"path": os.path.abspath(p), "length": st.st_size,
                     "modification_time": st.st_mtime, "content": content})
    return _partitioned(rows, num_partitions)


def decode_image_bytes(data: bytes) -> np.ndarray:
    """bytes -> [H, W, C] uint8 (RGB; grayscale promoted to 3 channels)."""
    from PIL import Image

    img = Image.open(_io.BytesIO(data))
    if img.mode not in ("RGB", "L"):
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = np.stack([arr] * 3, axis=-1)
    return arr.astype(np.uint8)


def read_image_files(path: str, recursive: bool = True, num_partitions: int = 1,
                     drop_invalid: bool = True) -> DataFrame:
    """Directory/glob -> rows of (path, image, height, width, channels).

    ``image`` is [H, W, C] uint8 — directly consumable by
    ``image.ImageTransformer`` (the PatchedImageFileFormat role)."""
    rows = []
    for p in _resolve_paths(path, recursive, _IMAGE_EXTS):
        with open(p, "rb") as f:
            data = f.read()
        try:
            arr = decode_image_bytes(data)
        except Exception:
            if drop_invalid:
                continue
            rows.append({"path": os.path.abspath(p), "image": None,
                         "height": 0, "width": 0, "channels": 0})
            continue
        rows.append({"path": os.path.abspath(p), "image": arr,
                     "height": arr.shape[0], "width": arr.shape[1],
                     "channels": arr.shape[2]})
    return _partitioned(rows, num_partitions)


# ---------------------------------------------------------------------------
# tabular file formats (the Spark csv/json DataSource roles)
# ---------------------------------------------------------------------------

def _read_tabular(path: str, what: str, loader, num_partitions: int | None,
                  max_rows: int | None = None) -> DataFrame:
    """Shared glob-or-literal resolution + one-DataFrame-partition-per-file
    union fold for the tabular readers. The path listing lives in
    ``_resolve_paths`` — the SAME resolver ``data.ShardedSource`` shards
    over, so eager and streamed reads can never list differently.

    ``max_rows`` is a fast path, not a post-filter: each file loads at most
    the remaining budget and files past the budget are never opened."""
    paths = resolve_input_paths(path, what)
    parts = []
    remaining = None if max_rows is None else max(int(max_rows), 0)
    for f in paths:
        if remaining is not None and remaining <= 0:
            break
        p = loader(f, remaining)
        if p is None:
            continue
        if remaining is not None:
            remaining -= p.count()
        parts.append(p)
    if not parts:
        return DataFrame.from_rows([])
    df = parts[0]
    for other in parts[1:]:
        df = df.union(other)
    return df.repartition(num_partitions) if num_partitions else df


def read_csv(path: str, num_partitions: int | None = None,
             max_rows: int | None = None, **pandas_kw) -> DataFrame:
    """CSV file(s)/glob/directory -> DataFrame; one PARTITION PER FILE
    (Spark's file-split model — header-only files stay as empty partitions
    so the file<->partition mapping holds), or repartitioned to
    ``num_partitions``. ``max_rows`` caps the TOTAL row count without
    parsing past the budget (pandas ``nrows`` per file; later files are
    never opened). Parsing is pandas' C engine (in-container); kwargs pass
    through (``dtype=``, ``usecols=``...)."""
    import pandas as pd

    def load(p, budget):
        kw = dict(pandas_kw)
        if budget is not None:  # compose with a caller-supplied nrows=
            kw["nrows"] = min(budget, kw["nrows"]) if "nrows" in kw else budget
        return DataFrame.from_pandas(pd.read_csv(p, **kw))

    return _read_tabular(path, "CSV", load, num_partitions, max_rows)


def write_csv(df: DataFrame, path: str, partitioned: bool = False) -> list[str]:
    """DataFrame -> CSV. ``partitioned=True`` writes ``part-NNNNN.csv`` files
    under ``path`` (the Spark output-directory layout; stale part files from
    a previous wider write are removed — they would silently merge into the
    next read); otherwise one file."""
    import pandas as pd

    written = []
    if partitioned:
        os.makedirs(path, exist_ok=True)
        for stale in _glob.glob(os.path.join(path, "part-*.csv")):
            os.remove(stale)
        for i, part in enumerate(df.partitions):
            out = os.path.join(path, f"part-{i:05d}.csv")
            pd.DataFrame({k: list(v) for k, v in part.items()}).to_csv(
                out, index=False)
            written.append(out)
        return written
    df.to_pandas().to_csv(path, index=False)
    return [path]


def read_jsonl(path: str, num_partitions: int | None = None,
               max_rows: int | None = None) -> DataFrame:
    """JSON-lines file(s)/glob -> DataFrame (one partition per file).

    Heterogeneous records are unioned over ALL keys seen in the file
    (missing fields become None) — JSONL rows rarely share an exact schema.
    ``max_rows`` caps the TOTAL row count and stops scanning (parsing AND
    file reads) the moment the budget is filled.
    """
    import json as _json

    def load(p, budget):
        rows = []
        with open(p) as f:
            for line in f:
                if budget is not None and len(rows) >= budget:
                    break
                if line.strip():
                    rows.append(_json.loads(line))
        if not rows:
            return None
        keys: list = []
        for r in rows:
            keys += [k for k in r if k not in keys]
        return DataFrame.from_rows([{k: r.get(k) for k in keys} for r in rows])

    return _read_tabular(path, "JSONL", load, num_partitions, max_rows)


def write_jsonl(df: DataFrame, path: str) -> str:
    """DataFrame -> one JSON-lines file (numpy scalars/arrays to plain JSON)."""
    import json as _json

    def default(o):
        if isinstance(o, np.ndarray):
            return o.tolist()
        if isinstance(o, np.generic):
            return o.item()
        if isinstance(o, bytes):
            return o.decode("utf-8", "replace")
        raise TypeError(f"not JSON-serializable: {type(o)}")

    with open(path, "w") as f:
        for part in df.partitions:
            cols = list(part.keys())
            n = len(next(iter(part.values()))) if cols else 0
            for i in range(n):
                f.write(_json.dumps({c: part[c][i] for c in cols},
                                    default=default) + "\n")
    return path
