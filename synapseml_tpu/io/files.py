"""Binary / image file data sources.

Reference: ``core/.../io/binary/BinaryFileFormat.scala`` (binary-file
DataSource: path/length/modificationTime/content rows) and
``org/apache/spark/ml/source/image/PatchedImageFileFormat.scala`` (image data
source decoding to the Spark image schema). Here the rows land in the columnar
DataFrame plane; images decode to [H, W, C] uint8 numpy (the layout
``image.ImageTransformer`` consumes).
"""

from __future__ import annotations

import glob as _glob
import io as _io
import os

import numpy as np

from ..core.dataframe import DataFrame

__all__ = ["read_binary_files", "read_image_files"]

_IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".tif", ".tiff", ".webp")


def _resolve_paths(path: str, recursive: bool, exts: tuple[str, ...] | None) -> list[str]:
    if os.path.isdir(path):
        pattern = os.path.join(path, "**" if recursive else "", "*")
        paths = _glob.glob(pattern, recursive=recursive)
    else:
        paths = _glob.glob(path, recursive=recursive)
    out = [p for p in paths if os.path.isfile(p)]
    if exts is not None:
        out = [p for p in out if p.lower().endswith(exts)]
    return sorted(out)


def _partitioned(rows: list[dict], num_partitions: int) -> DataFrame:
    if not rows:
        return DataFrame.from_rows([], num_partitions=1)
    return DataFrame.from_rows(rows, num_partitions=min(num_partitions, len(rows)))


def read_binary_files(path: str, recursive: bool = True, num_partitions: int = 1,
                      extensions: tuple[str, ...] | None = None) -> DataFrame:
    """Directory/glob -> rows of (path, length, modification_time, content).

    The ``BinaryFileFormat`` schema; ``content`` is raw bytes."""
    rows = []
    for p in _resolve_paths(path, recursive, extensions):
        st = os.stat(p)
        with open(p, "rb") as f:
            content = f.read()
        rows.append({"path": os.path.abspath(p), "length": st.st_size,
                     "modification_time": st.st_mtime, "content": content})
    return _partitioned(rows, num_partitions)


def decode_image_bytes(data: bytes) -> np.ndarray:
    """bytes -> [H, W, C] uint8 (RGB; grayscale promoted to 3 channels)."""
    from PIL import Image

    img = Image.open(_io.BytesIO(data))
    if img.mode not in ("RGB", "L"):
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = np.stack([arr] * 3, axis=-1)
    return arr.astype(np.uint8)


def read_image_files(path: str, recursive: bool = True, num_partitions: int = 1,
                     drop_invalid: bool = True) -> DataFrame:
    """Directory/glob -> rows of (path, image, height, width, channels).

    ``image`` is [H, W, C] uint8 — directly consumable by
    ``image.ImageTransformer`` (the PatchedImageFileFormat role)."""
    rows = []
    for p in _resolve_paths(path, recursive, _IMAGE_EXTS):
        with open(p, "rb") as f:
            data = f.read()
        try:
            arr = decode_image_bytes(data)
        except Exception:
            if drop_invalid:
                continue
            rows.append({"path": os.path.abspath(p), "image": None,
                         "height": 0, "width": 0, "channels": 0})
            continue
        rows.append({"path": os.path.abspath(p), "image": arr,
                     "height": arr.shape[0], "width": arr.shape[1],
                     "channels": arr.shape[2]})
    return _partitioned(rows, num_partitions)
