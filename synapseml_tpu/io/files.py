"""Binary / image file data sources.

Reference: ``core/.../io/binary/BinaryFileFormat.scala`` (binary-file
DataSource: path/length/modificationTime/content rows) and
``org/apache/spark/ml/source/image/PatchedImageFileFormat.scala`` (image data
source decoding to the Spark image schema). Here the rows land in the columnar
DataFrame plane; images decode to [H, W, C] uint8 numpy (the layout
``image.ImageTransformer`` consumes).
"""

from __future__ import annotations

import glob as _glob
import io as _io
import json as _json
import os
import struct as _struct
import threading as _threading

import numpy as np

from ..core.dataframe import DataFrame

__all__ = ["read_binary_files", "read_image_files", "read_csv", "write_csv",
           "read_jsonl", "write_jsonl", "resolve_input_paths",
           "json_default", "jsonl_writer", "npy_writer", "write_npy",
           "StreamedJsonlWriter", "StreamedNpyWriter"]

_IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".tif", ".tiff", ".webp")


def _resolve_paths(path: str, recursive: bool, exts: tuple[str, ...] | None) -> list[str]:
    if os.path.isdir(path):
        pattern = os.path.join(path, "**" if recursive else "", "*")
        paths = _glob.glob(pattern, recursive=recursive)
    else:
        paths = _glob.glob(path, recursive=recursive)
    out = [p for p in paths if os.path.isfile(p)]
    if exts is not None:
        out = [p for p in out if p.lower().endswith(exts)]
    return sorted(out)


def resolve_input_paths(path: str, what: str,
                        exts: tuple[str, ...] | None = None) -> list[str]:
    """THE glob-or-literal input resolver: a glob pattern or directory lists
    through ``_resolve_paths``; a literal filename passes through untouched.
    Shared by the eager tabular readers below and the streaming plane's
    ``data.ShardedSource``, so the two planes can never list differently."""
    is_glob = any(ch in path for ch in "*?[")
    paths = (_resolve_paths(path, recursive=True, exts=exts)
             if is_glob or os.path.isdir(path) else [path])
    if not paths:
        raise FileNotFoundError(f"no {what} files match {path!r}")
    return paths


def _partitioned(rows: list[dict], num_partitions: int) -> DataFrame:
    if not rows:
        return DataFrame.from_rows([], num_partitions=1)
    return DataFrame.from_rows(rows, num_partitions=min(num_partitions, len(rows)))


def read_binary_files(path: str, recursive: bool = True, num_partitions: int = 1,
                      extensions: tuple[str, ...] | None = None) -> DataFrame:
    """Directory/glob -> rows of (path, length, modification_time, content).

    The ``BinaryFileFormat`` schema; ``content`` is raw bytes."""
    rows = []
    for p in _resolve_paths(path, recursive, extensions):
        st = os.stat(p)
        with open(p, "rb") as f:
            content = f.read()
        rows.append({"path": os.path.abspath(p), "length": st.st_size,
                     "modification_time": st.st_mtime, "content": content})
    return _partitioned(rows, num_partitions)


def decode_image_bytes(data: bytes) -> np.ndarray:
    """bytes -> [H, W, C] uint8 (RGB; grayscale promoted to 3 channels)."""
    from PIL import Image

    img = Image.open(_io.BytesIO(data))
    if img.mode not in ("RGB", "L"):
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = np.stack([arr] * 3, axis=-1)
    return arr.astype(np.uint8)


def read_image_files(path: str, recursive: bool = True, num_partitions: int = 1,
                     drop_invalid: bool = True) -> DataFrame:
    """Directory/glob -> rows of (path, image, height, width, channels).

    ``image`` is [H, W, C] uint8 — directly consumable by
    ``image.ImageTransformer`` (the PatchedImageFileFormat role)."""
    rows = []
    for p in _resolve_paths(path, recursive, _IMAGE_EXTS):
        with open(p, "rb") as f:
            data = f.read()
        try:
            arr = decode_image_bytes(data)
        except Exception:
            if drop_invalid:
                continue
            rows.append({"path": os.path.abspath(p), "image": None,
                         "height": 0, "width": 0, "channels": 0})
            continue
        rows.append({"path": os.path.abspath(p), "image": arr,
                     "height": arr.shape[0], "width": arr.shape[1],
                     "channels": arr.shape[2]})
    return _partitioned(rows, num_partitions)


# ---------------------------------------------------------------------------
# tabular file formats (the Spark csv/json DataSource roles)
# ---------------------------------------------------------------------------

def _read_tabular(path: str, what: str, loader, num_partitions: int | None,
                  max_rows: int | None = None) -> DataFrame:
    """Shared glob-or-literal resolution + one-DataFrame-partition-per-file
    union fold for the tabular readers. The path listing lives in
    ``_resolve_paths`` — the SAME resolver ``data.ShardedSource`` shards
    over, so eager and streamed reads can never list differently.

    ``max_rows`` is a fast path, not a post-filter: each file loads at most
    the remaining budget and files past the budget are never opened."""
    paths = resolve_input_paths(path, what)
    parts = []
    remaining = None if max_rows is None else max(int(max_rows), 0)
    for f in paths:
        if remaining is not None and remaining <= 0:
            break
        p = loader(f, remaining)
        if p is None:
            continue
        if remaining is not None:
            remaining -= p.count()
        parts.append(p)
    if not parts:
        return DataFrame.from_rows([])
    df = parts[0]
    for other in parts[1:]:
        df = df.union(other)
    return df.repartition(num_partitions) if num_partitions else df


def read_csv(path: str, num_partitions: int | None = None,
             max_rows: int | None = None, **pandas_kw) -> DataFrame:
    """CSV file(s)/glob/directory -> DataFrame; one PARTITION PER FILE
    (Spark's file-split model — header-only files stay as empty partitions
    so the file<->partition mapping holds), or repartitioned to
    ``num_partitions``. ``max_rows`` caps the TOTAL row count without
    parsing past the budget (pandas ``nrows`` per file; later files are
    never opened). Parsing is pandas' C engine (in-container); kwargs pass
    through (``dtype=``, ``usecols=``...)."""
    import pandas as pd

    def load(p, budget):
        kw = dict(pandas_kw)
        if budget is not None:  # compose with a caller-supplied nrows=
            kw["nrows"] = min(budget, kw["nrows"]) if "nrows" in kw else budget
        return DataFrame.from_pandas(pd.read_csv(p, **kw))

    return _read_tabular(path, "CSV", load, num_partitions, max_rows)


def write_csv(df: DataFrame, path: str, partitioned: bool = False) -> list[str]:
    """DataFrame -> CSV. ``partitioned=True`` writes ``part-NNNNN.csv`` files
    under ``path`` (the Spark output-directory layout; stale part files from
    a previous wider write are removed — they would silently merge into the
    next read); otherwise one file."""
    import pandas as pd

    written = []
    if partitioned:
        os.makedirs(path, exist_ok=True)
        for stale in _glob.glob(os.path.join(path, "part-*.csv")):
            os.remove(stale)
        for i, part in enumerate(df.partitions):
            out = os.path.join(path, f"part-{i:05d}.csv")
            pd.DataFrame({k: list(v) for k, v in part.items()}).to_csv(
                out, index=False)
            written.append(out)
        return written
    df.to_pandas().to_csv(path, index=False)
    return [path]


def read_jsonl(path: str, num_partitions: int | None = None,
               max_rows: int | None = None) -> DataFrame:
    """JSON-lines file(s)/glob -> DataFrame (one partition per file).

    Heterogeneous records are unioned over ALL keys seen in the file
    (missing fields become None) — JSONL rows rarely share an exact schema.
    ``max_rows`` caps the TOTAL row count and stops scanning (parsing AND
    file reads) the moment the budget is filled. A malformed record raises
    ``ValueError`` naming the file and line number (a bare
    ``json.JSONDecodeError`` pointed at nothing when the glob matched
    thousands of part files).
    """

    def load(p, budget):
        rows = []
        with open(p) as f:
            for lineno, line in enumerate(f, 1):
                if budget is not None and len(rows) >= budget:
                    break
                if line.strip():
                    rows.append(loads_jsonl_line(line, p, lineno))
        if not rows:
            return None
        keys: list = []
        for r in rows:
            keys += [k for k in r if k not in keys]
        return DataFrame.from_rows([{k: r.get(k) for k in keys} for r in rows])

    return _read_tabular(path, "JSONL", load, num_partitions, max_rows)


def write_jsonl(df: DataFrame, path: str) -> str:
    """DataFrame -> one JSON-lines file (numpy scalars/arrays to plain JSON).
    Atomic: readers see the previous file or the complete new one, never a
    torn write (the streamed-writer temp + rename discipline)."""
    with jsonl_writer(path) as w:
        for part in df.partitions:
            n = len(next(iter(part.values()))) if part else 0
            w.write_columns(part, n)
    return path


def loads_jsonl_line(line: str | bytes, path: str, lineno: int) -> dict:
    """``json.loads`` for one JSONL record that, on a malformed line, names
    the file and line instead of raising a bare ``JSONDecodeError`` (shared
    with the streaming plane's byte-range reader)."""
    try:
        return _json.loads(line)
    except _json.JSONDecodeError as e:
        snippet = line if isinstance(line, str) else \
            line.decode("utf-8", "replace")
        snippet = snippet.strip()
        if len(snippet) > 120:
            snippet = snippet[:120] + "..."
        raise ValueError(
            f"{path}:{lineno}: malformed JSONL record ({e.msg} at column "
            f"{e.colno}): {snippet!r}") from e


# ---------------------------------------------------------------------------
# streamed atomic writers (shared with the scoring sink — scoring/sink.py)
# ---------------------------------------------------------------------------

def json_default(o):
    """The one numpy/bytes -> plain-JSON coercion used by every JSONL
    writer (DataFrame ``write_jsonl`` and the scoring sink part files)."""
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, np.generic):
        return o.item()
    if isinstance(o, bytes):
        return o.decode("utf-8", "replace")
    raise TypeError(f"not JSON-serializable: {type(o)}")


def _tmp_path(path: str) -> str:
    """Same-directory per-writer temp name (pid + thread id — the
    ``registry/store`` atomic-write discipline: two threads writing the same
    destination cannot interleave into one temp file)."""
    return f"{path}.tmp.{os.getpid()}.{_threading.get_ident()}"


class _StreamedWriterBase:
    """Write-to-temp / rename-on-commit lifecycle shared by the streamed
    writers: :meth:`commit` makes the destination appear atomically
    (``os.replace``, after flush + fsync — a crashed writer can never leave
    a torn file under the final name), :meth:`abort` removes the temp.
    Context-manager use commits on a clean exit and aborts on exception."""

    def __init__(self, path: str):
        self.path = path
        self._tmp = _tmp_path(path)
        self._f = None
        self.rows = 0

    def _finish_payload(self) -> None:
        """Subclass hook: last bytes before the fsync (e.g. the npy header
        rewrite)."""

    def commit(self) -> str:
        if self._f is None:
            raise RuntimeError(f"writer for {self.path!r} already closed")
        self._finish_payload()
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        self._f = None
        os.replace(self._tmp, self.path)
        return self.path

    def abort(self) -> None:
        """Drop the temp file; the destination is untouched. Idempotent."""
        if self._f is not None:
            try:
                self._f.close()
            finally:
                self._f = None
        try:
            os.unlink(self._tmp)
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is None:
            self.commit()
        else:
            self.abort()
        return False


class StreamedJsonlWriter(_StreamedWriterBase):
    """Streamed JSONL writer: append rows (or columnar chunks) in bounded
    memory; the destination file appears atomically on :meth:`commit`."""

    def __init__(self, path: str):
        super().__init__(path)
        self._f = open(self._tmp, "w")

    def write_row(self, row: dict) -> None:
        self._f.write(_json.dumps(row, default=json_default) + "\n")
        self.rows += 1

    def write_columns(self, cols: dict, n: int | None = None) -> None:
        """Append a columnar chunk as ``n`` rows (``n`` defaults to the
        first column's length)."""
        names = list(cols.keys())
        if n is None:
            n = len(next(iter(cols.values()))) if names else 0
        for i in range(int(n)):
            self.write_row({c: cols[c][i] for c in names})


def jsonl_writer(path: str) -> StreamedJsonlWriter:
    """Streamed atomic JSONL writer (see :class:`StreamedJsonlWriter`)."""
    return StreamedJsonlWriter(path)


_NPY_MAGIC = b"\x93NUMPY\x01\x00"
_NPY_HEADER_LEN = 118  # dict bytes; total header = 10 + 118 = 128 (64-aligned)


def _npy_header(dtype: np.dtype, shape: tuple) -> bytes:
    """A fixed-length (128-byte) npy 1.0 header, so the shape can be
    rewritten in place once the final row count is known — the standard
    append-then-fixup trick for streaming ``.npy`` emission."""
    from numpy.lib import format as _npfmt

    body = ("{'descr': %r, 'fortran_order': False, 'shape': %r, }"
            % (_npfmt.dtype_to_descr(dtype), tuple(int(d) for d in shape))
            ).encode("latin1")
    if len(body) > _NPY_HEADER_LEN - 1:
        raise ValueError(f"npy header too large for the fixed slot: {body!r}")
    body = body + b" " * (_NPY_HEADER_LEN - 1 - len(body)) + b"\n"
    return _NPY_MAGIC + _struct.pack("<H", _NPY_HEADER_LEN) + body


class StreamedNpyWriter(_StreamedWriterBase):
    """Streamed ``.npy`` writer: append row-chunks of one array without
    knowing the total row count up front. The header is written with a
    placeholder shape on the first :meth:`append` (which pins dtype and
    trailing shape) and rewritten in place at :meth:`commit`; the file then
    appears atomically via rename. ``np.load`` reads the result like any
    eagerly saved array."""

    def __init__(self, path: str):
        super().__init__(path)
        self._f = open(self._tmp, "wb")
        self._dtype: np.dtype | None = None
        self._trailing: tuple | None = None

    def append(self, arr: np.ndarray) -> None:
        arr = np.ascontiguousarray(arr)
        if arr.dtype == object:
            raise TypeError("cannot stream an object-dtype column to .npy; "
                            "featurize it into a rectangular array first")
        if arr.ndim == 0:
            raise ValueError("append needs rows along a leading dimension; "
                             "got a 0-d scalar (np.atleast_1d it first)")
        if self._dtype is None:
            self._dtype = arr.dtype
            self._trailing = tuple(arr.shape[1:])
            self._f.write(_npy_header(self._dtype, (0,) + self._trailing))
        elif arr.dtype != self._dtype or tuple(arr.shape[1:]) != self._trailing:
            raise ValueError(
                f"chunk dtype/shape {arr.dtype}{tuple(arr.shape[1:])} does "
                f"not match the stream's {self._dtype}{self._trailing}")
        self._f.write(arr.tobytes())
        self.rows += int(arr.shape[0])

    def _finish_payload(self) -> None:
        if self._dtype is None:  # zero appends: a legal empty float64 array
            self._dtype, self._trailing = np.dtype(np.float64), ()
            self._f.write(_npy_header(self._dtype, (0,)))
        self._f.seek(0)
        self._f.write(_npy_header(self._dtype, (self.rows,) + self._trailing))
        self._f.seek(0, os.SEEK_END)


def npy_writer(path: str) -> StreamedNpyWriter:
    """Streamed atomic ``.npy`` writer (see :class:`StreamedNpyWriter`)."""
    return StreamedNpyWriter(path)


def write_npy(path: str, array: np.ndarray) -> str:
    """One array -> one ``.npy`` file, atomically (temp + rename).
    Scalars save as shape ``(1,)`` (the streamed writer needs a leading
    row dimension)."""
    with npy_writer(path) as w:
        w.append(np.atleast_1d(np.asarray(array)))
    return path
