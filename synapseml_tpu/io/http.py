"""HTTP-on-DataFrame: requests/responses as column values.

Reference (SURVEY.md §2.5): ``io/http/HTTPSchema.scala`` (request/response
structs), ``HTTPClients.scala`` (``HandlingUtils.advancedUDF`` retry/backoff/
429 handling :66-230, ``AsyncHTTPClient`` :232), ``Clients.scala:12-66``
(buffered async futures), ``HTTPTransformer.scala:97-152``,
``SimpleHTTPTransformer.scala:66-182`` and ``Parsers.scala``.

Python-native: stdlib ``urllib`` for transport (zero deps), a thread pool for
the async buffered client (the reference's concurrency/concurrentTimeout
params), jittered exponential backoff honoring Retry-After on 429/503.

Resilience: retries run through ``core/resilience.py`` (``RetryPolicy`` with
FULL jitter + optional ``RetryBudget``; an optional ``Deadline`` caps every
attempt's timeout so total latency is bounded), instrumented on
``resilience_measures("http")``; ``core/faults.py`` fault plans hook the
``_urlopen`` send path for offline fault-injection tests.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import datetime
import email.utils
import json
import math
import time
import urllib.error
import urllib.request

import numpy as np

from ..core import faults as _faults
from ..core import observability as obs
from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param, TypeConverters
from ..core.pipeline import Transformer
from ..core.resilience import (
    Deadline,
    DeadlineExpired,
    RetryPolicy,
    resilience_measures,
)


# hot-path metric handles, re-resolved only when the registry is replaced
_HTTP_METRICS = obs.HandleCache(lambda reg: {
    "retries": reg.counter(
        "synapseml_http_retries_total",
        "client retries by plane and trigger (HTTP status or 'connect')",
        ("plane", "status")),
    "request_ms": reg.histogram(
        "synapseml_http_request_duration_ms",
        "send_with_retries total latency (all attempts)", ("method",)),
    "requests": reg.counter(
        "synapseml_http_requests_total",
        "send_with_retries outcomes by status class", ("method", "status")),
})

__all__ = ["HTTPRequest", "HTTPResponse", "send_with_retries", "AsyncHTTPClient",
           "HTTPTransformer", "SimpleHTTPTransformer", "JSONInputParser",
           "JSONOutputParser", "CustomInputParser", "StringOutputParser"]


@dataclasses.dataclass
class HTTPRequest:
    """(ref ``HTTPSchema.scala`` HTTPRequestData)"""

    url: str
    method: str = "GET"
    headers: dict = dataclasses.field(default_factory=dict)
    entity: bytes | str | None = None

    def to_urllib(self) -> urllib.request.Request:
        data = self.entity
        if isinstance(data, str):
            data = data.encode("utf-8")
        return urllib.request.Request(self.url, data=data, method=self.method,
                                      headers=dict(self.headers))


@dataclasses.dataclass
class HTTPResponse:
    """(ref ``HTTPSchema.scala`` HTTPResponseData)"""

    status_code: int
    reason: str = ""
    headers: dict = dataclasses.field(default_factory=dict)
    entity: bytes = b""
    error: str | None = None

    @property
    def text(self) -> str:
        return self.entity.decode("utf-8", "replace")

    def json(self):
        return json.loads(self.text)


_RETRY_STATUSES = (429, 500, 502, 503, 504)

# Retry-After clamp: negative (clock skew / past HTTP-date) waits become 0,
# absurd server-sent waits are capped so one bad header can't stall a lane
RETRY_AFTER_CAP_MS = 30_000.0


def _retry_after_ms(value) -> float | None:
    """Parse a Retry-After header: delta-seconds or an HTTP-date (RFC 9110
    §10.2.3, via ``email.utils.parsedate_to_datetime``). None when absent or
    unparseable (caller falls back to the backoff schedule); clamped to
    [0, RETRY_AFTER_CAP_MS]."""
    if value is None:
        return None
    try:
        sec = float(value)
    except (TypeError, ValueError):
        try:
            dt = email.utils.parsedate_to_datetime(str(value))
        except (TypeError, ValueError):
            return None
        if dt.tzinfo is None:   # RFC 5322 fallback: naive means UTC
            dt = dt.replace(tzinfo=datetime.timezone.utc)
        sec = (dt - datetime.datetime.now(datetime.timezone.utc)).total_seconds()
    if not math.isfinite(sec):  # 'Retry-After: nan'/'inf' parse as floats but
        return None             # would poison the sleep below
    return min(max(sec, 0.0) * 1000.0, RETRY_AFTER_CAP_MS)


def _urlopen(request: HTTPRequest, timeout_s: float):
    """The one send hook: an active fault plan (``core/faults.py``) may raise
    an injected error or add latency before the real request goes out."""
    plan = _faults.active_fault_plan()
    if plan is not None:
        plan.on_http_send(request.url)
    return urllib.request.urlopen(request.to_urllib(), timeout=timeout_s)


def send_with_retries(request: HTTPRequest, backoffs_ms=(100, 500, 1000),
                      timeout_s: float = 60.0,
                      policy: RetryPolicy | None = None,
                      deadline: Deadline | None = None,
                      trace_parent=None) -> HTTPResponse:
    """(ref ``HandlingUtils.advancedUDF`` — retry on 429/5xx with jittered
    backoff, honoring Retry-After.) Network errors after the last retry return
    a response row with ``error`` set rather than raising (errors-as-data,
    like the reference's error column).

    ``policy`` (default: ``RetryPolicy(backoffs_ms)``) adds full jitter and an
    optional retry budget — when the budget is drained the call fails fast
    instead of amplifying a storm. ``deadline`` caps every attempt's timeout
    by the remaining total budget; on expiry the last known response/error is
    returned with ``deadline_expired`` counted.

    Observability: the whole call (all attempts) runs in one ``http.request``
    span — ``trace_parent`` (a ``SpanContext``) pins it to the caller's trace
    when the send happens on a pool thread — its context is injected as a
    W3C ``traceparent`` header, the total latency lands in the
    ``synapseml_http_request_duration_ms`` histogram, and every retry counts
    on ``synapseml_http_retries_total`` by trigger status."""
    tracer = obs.get_tracer()
    t0 = time.perf_counter()
    resp = None
    try:
        with tracer.span("http.request",
                         {"url": request.url, "method": request.method},
                         parent=trace_parent):
            hdrs = dict(request.headers)
            tracer.inject(hdrs)
            request = dataclasses.replace(request, headers=hdrs)
            resp = _send_with_retries(request, backoffs_ms, timeout_s, policy,
                                      deadline)
        return resp
    finally:
        # metric emission in finally: an unexpected exception (bad scheme,
        # a bug below) must not let requests_total diverge from span counts
        m = _HTTP_METRICS.get()
        m["request_ms"].observe((time.perf_counter() - t0) * 1e3,
                                method=request.method)
        if resp is None:
            status = "exception"
        elif resp.status_code:
            status = f"{resp.status_code // 100}xx"
        else:
            status = ("deadline" if "deadline" in (resp.reason or "")
                      else "error")
        m["requests"].inc(method=request.method, status=status)


def _send_with_retries(request: HTTPRequest, backoffs_ms, timeout_s: float,
                       policy: RetryPolicy | None,
                       deadline: Deadline | None) -> HTTPResponse:
    policy = policy if policy is not None \
        else RetryPolicy(backoffs_ms=tuple(backoffs_ms))
    m = resilience_measures("http")
    last_err = None
    for attempt in range(policy.max_attempts):
        try:
            attempt_timeout = timeout_s if deadline is None \
                else deadline.cap(timeout_s)
        except DeadlineExpired:
            m.count("deadline_expired")
            return HTTPResponse(status_code=0, reason="deadline expired",
                                error=f"deadline expired: {last_err}")
        try:
            with _urlopen(request, attempt_timeout) as r:
                policy.on_success(first_attempt=attempt == 0)
                return HTTPResponse(status_code=r.status, reason=r.reason or "",
                                    headers=dict(r.headers), entity=r.read())
        except urllib.error.HTTPError as e:
            body = e.read() if hasattr(e, "read") else b""
            if e.code in _RETRY_STATUSES and attempt < policy.max_attempts - 1:
                wait_ms = _retry_after_ms(
                    e.headers.get("Retry-After") if e.headers else None)
                if wait_ms is None:
                    wait_ms = policy.backoff_ms(attempt)
                # deadline first — a refused sleep must not burn a budget token
                if deadline is not None and \
                        not deadline.sleep_allowed(wait_ms / 1000.0):
                    m.count("deadline_expired")
                elif policy.acquire_retry():
                    m.count("retry")
                    _HTTP_METRICS.get()["retries"].inc(plane="http",
                                                status=str(e.code))
                    time.sleep(wait_ms / 1000.0)
                    last_err = e
                    continue
            return HTTPResponse(status_code=e.code, reason=str(e.reason),
                                headers=dict(e.headers or {}), entity=body)
        except (urllib.error.URLError, OSError) as e:
            last_err = e
            if attempt < policy.max_attempts - 1:
                wait_ms = policy.backoff_ms(attempt)
                if deadline is not None and \
                        not deadline.sleep_allowed(wait_ms / 1000.0):
                    m.count("deadline_expired")
                elif policy.acquire_retry():
                    m.count("retry")
                    _HTTP_METRICS.get()["retries"].inc(plane="http",
                                                status="connect")
                    time.sleep(wait_ms / 1000.0)
                    continue
            return HTTPResponse(status_code=0, reason="connection error",
                                error=str(last_err))
    return HTTPResponse(status_code=0, reason="unreachable", error=str(last_err))


class AsyncHTTPClient:
    """Buffered-future client (ref ``AsyncHTTPClient`` ``HTTPClients.scala:232``,
    ``Clients.scala:48-66``): up to ``concurrency`` requests in flight,
    responses returned in request order."""

    def __init__(self, concurrency: int = 8, timeout_s: float = 60.0,
                 backoffs_ms=(100, 500, 1000),
                 policy: RetryPolicy | None = None,
                 deadline: Deadline | None = None):
        self.concurrency = max(int(concurrency), 1)
        self.timeout_s = timeout_s
        self.backoffs_ms = tuple(backoffs_ms)
        # one shared policy per client: the retry BUDGET is a per-client
        # token bucket, so a storm across the whole pool drains one bucket
        self.policy = policy if policy is not None \
            else RetryPolicy(backoffs_ms=self.backoffs_ms)
        self.deadline = deadline
        self._pool: concurrent.futures.ThreadPoolExecutor | None = None

    def _executor(self) -> concurrent.futures.ThreadPoolExecutor:
        # one long-lived pool per client: repeated send_all calls (e.g. LRO
        # polling sweeps) must not pay thread creation each time
        if self._pool is None:
            self._pool = concurrent.futures.ThreadPoolExecutor(self.concurrency)
        return self._pool

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def __del__(self):  # best-effort cleanup
        try:
            self.close()
        except Exception:
            pass

    def send_all(self, requests: list[HTTPRequest | None],
                 deadline: Deadline | None = None) -> list[HTTPResponse | None]:
        pool = self._executor()
        deadline = deadline if deadline is not None else self.deadline
        # capture the calling thread's span context so the pool threads'
        # http.request spans stay in the caller's trace (thread-local
        # context does not cross the executor boundary by itself)
        parent = obs.get_tracer().current_context()
        futures = [None if r is None else
                   pool.submit(send_with_retries, r, self.backoffs_ms,
                               self.timeout_s, self.policy, deadline, parent)
                   for r in requests]
        return [None if f is None else f.result() for f in futures]


class HTTPTransformer(Transformer):
    """request col (HTTPRequest or None) -> response col
    (ref ``HTTPTransformer.scala:97-152``; None rows pass through as None,
    matching the reference's null handling)."""

    feature_name = "io_http"

    input_col = Param("input_col", "HTTPRequest column", default="request")
    output_col = Param("output_col", "HTTPResponse column", default="response")
    concurrency = Param("concurrency", "in-flight requests per partition",
                        default=8, converter=TypeConverters.to_int)
    timeout_s = Param("timeout_s", "per-request timeout seconds", default=60.0,
                      converter=TypeConverters.to_float)
    backoffs_ms = ComplexParam("backoffs_ms", "retry backoff schedule",
                               default=(100, 500, 1000))
    retry_policy = ComplexParam("retry_policy", "core.resilience.RetryPolicy "
                                "(overrides backoffs_ms; carries jitter rng "
                                "and retry budget)", default=None)

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        client = AsyncHTTPClient(self.get("concurrency"), self.get("timeout_s"),
                                 self.get("backoffs_ms"),
                                 policy=self.get("retry_policy"))

        def per_part(p):
            reqs = list(p[self.get("input_col")])
            resps = client.send_all(reqs)
            out = np.empty(len(resps), dtype=object)
            out[:] = resps
            q = dict(p)
            q[self.get("output_col")] = out
            return q

        return df.map_partitions(per_part)


# ---------------------------------------------------------------------------
# parsers (ref Parsers.scala)
# ---------------------------------------------------------------------------

class JSONInputParser:
    """row dict -> POST HTTPRequest with a JSON body (ref ``JSONInputParser``)."""

    def __init__(self, url: str, headers: dict | None = None, method: str = "POST"):
        self.url = url
        self.headers = {"Content-Type": "application/json", **(headers or {})}
        self.method = method

    def __call__(self, row: dict) -> HTTPRequest:
        clean = {k: (v.item() if isinstance(v, np.generic) else
                     v.tolist() if isinstance(v, np.ndarray) else v)
                 for k, v in row.items()}
        return HTTPRequest(url=self.url, method=self.method, headers=self.headers,
                           entity=json.dumps(clean))


class CustomInputParser:
    """Arbitrary row -> HTTPRequest function (ref ``CustomInputParser``)."""

    def __init__(self, fn):
        self.fn = fn

    def __call__(self, row: dict) -> HTTPRequest:
        return self.fn(row)


class JSONOutputParser:
    """HTTPResponse -> parsed JSON (ref ``JSONOutputParser``); non-2xx or
    unparseable -> None (the error column carries the reason)."""

    def __call__(self, resp: HTTPResponse | None):
        if resp is None or resp.status_code // 100 != 2:
            return None
        try:
            return resp.json()
        except (json.JSONDecodeError, UnicodeDecodeError):
            return None


class StringOutputParser:
    def __call__(self, resp: HTTPResponse | None):
        return None if resp is None else resp.text


class SimpleHTTPTransformer(Transformer):
    """input parser -> HTTPTransformer -> output parser, with an errors column
    for failed rows (ref ``SimpleHTTPTransformer.scala:66-182``)."""

    feature_name = "io_http"

    input_col = Param("input_col", "column fed to the input parser", default="input")
    output_col = Param("output_col", "parsed output column", default="output")
    error_col = Param("error_col", "per-row error column", default="errors")
    input_parser = ComplexParam("input_parser", "row -> HTTPRequest callable")
    output_parser = ComplexParam("output_parser", "HTTPResponse -> value callable",
                                 default=None)
    concurrency = Param("concurrency", "in-flight requests", default=8,
                        converter=TypeConverters.to_int)
    timeout_s = Param("timeout_s", "request timeout", default=60.0,
                      converter=TypeConverters.to_float)
    backoffs_ms = ComplexParam("backoffs_ms", "retry backoff schedule",
                               default=(100, 500, 1000))
    retry_policy = ComplexParam("retry_policy", "core.resilience.RetryPolicy "
                                "(overrides backoffs_ms)", default=None)

    def _transform(self, df: DataFrame) -> DataFrame:
        self.require_columns(df, self.get("input_col"))
        parser = self.get("input_parser")
        out_parser = self.get("output_parser") or JSONOutputParser()
        http = HTTPTransformer(
            input_col="_http_request", output_col="_http_response",
            concurrency=self.get("concurrency"), timeout_s=self.get("timeout_s"),
            backoffs_ms=self.get("backoffs_ms"),
            retry_policy=self.get("retry_policy"))

        def build_requests(p):
            col = p[self.get("input_col")]
            reqs = np.empty(len(col), dtype=object)
            for i, v in enumerate(col):
                row = v if isinstance(v, dict) else {self.get("input_col"): v}
                reqs[i] = None if v is None else parser(row)
            return reqs

        with_req = df.with_column("_http_request", build_requests)
        responded = http.transform(with_req)

        def parse(p):
            resps = p["_http_response"]
            parsed = np.empty(len(resps), dtype=object)
            errors = np.empty(len(resps), dtype=object)
            for i, r in enumerate(resps):
                parsed[i] = out_parser(r)
                if r is None:
                    errors[i] = None
                elif r.error or r.status_code // 100 != 2:
                    errors[i] = r.error or f"HTTP {r.status_code}: {r.reason}"
                else:
                    errors[i] = None
            q = dict(p)
            q[self.get("output_col")] = parsed
            q[self.get("error_col")] = errors
            return q

        return responded.map_partitions(parse).drop("_http_request", "_http_response")
